"""Virtual multi-node cluster fixture (reference:
python/ray/cluster_utils.py:99 — the canonical pattern for scheduler and
fault-tolerance tests: several raylets with faked resources on one machine)."""
from __future__ import annotations

from typing import Dict, Optional

import ray_tpu


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 connect: bool = False):
        self.nodes = []
        self.head_node = None
        if initialize_head:
            args = dict(head_node_args or {})
            self.head_node = self.add_node(**args)
            if connect:
                self.connect()

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: int = 512 * 1024**2,
                 labels: Optional[dict] = None, **kw):
        res = dict(resources or {})
        if num_cpus:
            res["CPU"] = float(num_cpus)
        if num_tpus:
            res["TPU"] = float(num_tpus)
        if self.head_node is None and not ray_tpu.is_initialized():
            # First node: boot the head (driver not yet connected).
            node_id = ray_tpu._boot_head(res, labels, object_store_memory)
        else:
            node_id = ray_tpu._global_head().add_node(
                res, labels, store_capacity=object_store_memory)
        self.nodes.append(node_id)
        return node_id

    def remove_node(self, node_id):
        ray_tpu._global_head().remove_node(node_id)
        if node_id in self.nodes:
            self.nodes.remove(node_id)

    def connect(self):
        ray_tpu._connect_driver()

    def shutdown(self):
        ray_tpu.shutdown()
