"""OpenTelemetry tracing integration.

Reference: python/ray/util/tracing/tracing_helper.py — the runtime is
instrumented against the opentelemetry *API* (present in this image);
span data goes wherever the application's TracerProvider sends it, so
wiring an SDK/exporter is the user's call exactly as in the reference
(`ray.init(_tracing_startup_hook=...)`).  Without a provider the API's
no-op tracer makes every span free.

Surface:
- ``enable_tracing()`` / ``tracing_enabled()`` — process-local switch
  (also on via the ``tracing_enabled`` config flag / RAY_TPU_TRACING_ENABLED).
- ``span(name, **attrs)`` — context manager used at the runtime's
  instrumentation points (task submit, task execute, actor calls).
- Spans ALSO land in a process-local buffer (``pop_local_spans``) so
  `ray_tpu.timeline()`-style tooling sees them even with no SDK.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_enabled: Optional[bool] = None
_local_spans: List[Dict[str, Any]] = []
_MAX_LOCAL_SPANS = 10_000


def enable_tracing():
    global _enabled
    _enabled = True


def disable_tracing():
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    global _enabled
    if _enabled is None:
        from ray_tpu._private.config import CONFIG

        _enabled = bool(CONFIG.tracing_enabled)
    return _enabled


def _tracer():
    try:
        from opentelemetry import trace

        return trace.get_tracer("ray_tpu")
    except Exception:
        return None


@contextlib.contextmanager
def span(name: str, **attributes):
    """Instrumentation point: otel span (no-op without a provider) plus a
    local record for timeline tooling."""
    if not tracing_enabled():
        yield
        return
    t0 = time.time()
    tracer = _tracer()
    ctx = (tracer.start_as_current_span(name, attributes=attributes)
           if tracer is not None else contextlib.nullcontext())
    try:
        with ctx:
            yield
    finally:
        rec = {"name": name, "start": t0, "end": time.time(),
               "attributes": attributes}
        with _lock:
            _local_spans.append(rec)
            if len(_local_spans) > _MAX_LOCAL_SPANS:
                del _local_spans[: len(_local_spans) - _MAX_LOCAL_SPANS]


def pop_local_spans() -> List[Dict[str, Any]]:
    with _lock:
        out, _local_spans[:] = list(_local_spans), []
        return out
