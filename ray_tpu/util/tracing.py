"""OpenTelemetry tracing integration.

Reference: python/ray/util/tracing/tracing_helper.py — the runtime is
instrumented against the opentelemetry *API* (present in this image);
span data goes wherever the application's TracerProvider sends it, so
wiring an SDK/exporter is the user's call exactly as in the reference
(`ray.init(_tracing_startup_hook=...)`).  Without a provider the API's
no-op tracer makes every span free.

Surface:
- ``enable_tracing()`` / ``tracing_enabled()`` — process-local switch
  (also on via the ``tracing_enabled`` config flag / RAY_TPU_TRACING_ENABLED).
- ``span(name, **attrs)`` — context manager used at the runtime's
  instrumentation points (task submit, task execute, actor calls).
  Each span joins the active distributed trace context
  (ray_tpu.observability) and becomes the active parent for anything
  submitted inside it, so cross-process timelines assemble.
- Spans ALSO land in a process-local ring (``pop_local_spans``) so
  `ray_tpu.timeline()`-style tooling sees them even with no SDK.  The
  ring is the shared drop-oldest primitive (observability.SpanRing) —
  overflow is counted, not silently truncated, and the counter is
  exported as ``tracing_spans_dropped_total`` through util.metrics.
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional

_enabled: Optional[bool] = None
_local_ring = None  # observability.SpanRing, created on first span


def enable_tracing():
    global _enabled
    _enabled = True


def disable_tracing():
    global _enabled
    _enabled = False
    # The tracing session's implicit driver context dies with it:
    # obs.ensure_context() installs one on this thread at API boundaries,
    # and a leftover would absorb the next session's spans into a stale
    # rootless trace.
    from ray_tpu import observability as _obs

    _obs.clear_context()


def tracing_enabled() -> bool:
    global _enabled
    if _enabled is None:
        from ray_tpu._private.config import CONFIG

        _enabled = bool(CONFIG.tracing_enabled)
    return _enabled


def _tracer():
    try:
        from opentelemetry import trace

        return trace.get_tracer("ray_tpu")
    except Exception:
        return None


def _ring():
    global _local_ring
    if _local_ring is None:
        from ray_tpu import observability as obs

        _local_ring = obs.SpanRing(10_000)
    return _local_ring


def spans_dropped_total() -> int:
    """Local-buffer drops (the process ring counts its own separately)."""
    return _local_ring.dropped_total if _local_ring is not None else 0


@contextlib.contextmanager
def span(name: str, **attributes):
    """Instrumentation point: otel span (no-op without a provider) plus a
    local record for timeline tooling.  Joins the active trace context
    and is the active parent for nested work while open."""
    if not tracing_enabled():
        yield
        return
    from ray_tpu import observability as obs

    t0 = time.time()
    tracer = _tracer()
    otel = (tracer.start_as_current_span(name, attributes=attributes)
            if tracer is not None else contextlib.nullcontext())
    parent = obs.get_context()
    trace_id = parent[0] if parent else obs.new_id()
    parent_id = parent[1] if parent else None
    sid = obs.new_id()
    old = obs.set_context((trace_id, sid))
    try:
        with otel:
            yield
    finally:
        obs.set_context(old)
        end = time.time()
        _ring().append({"name": name, "start": t0, "end": end,
                        "trace_id": trace_id, "span_id": sid,
                        "parent_id": parent_id, "attributes": attributes})
        obs.record(name, t0, end, ctx=(trace_id, sid), parent_id=parent_id,
                   span_id=sid, **attributes)


def pop_local_spans() -> List[Dict[str, Any]]:
    r = _local_ring
    if r is None:
        return []
    spans = r.drain()
    _export_dropped(r)
    return spans


_dropped_exported = 0


def _export_dropped(r) -> None:
    """Ship the drop-counter delta into util.metrics, off the hot path
    (drain cadence only) and best-effort (needs a live driver KV)."""
    global _dropped_exported
    delta = r.dropped_total - _dropped_exported
    if delta <= 0:
        return
    try:
        from ray_tpu.util.metrics import Counter

        Counter("tracing_spans_dropped_total",
                "spans dropped by full ring buffers").inc(delta)
        _dropped_exported += delta
    except Exception:
        pass
