"""Actor-level collective API (reference: python/ray/util/collective/
collective.py — init_collective_group :120, allreduce :258, etc., over
cupy-NCCL groups with a named-actor rendezvous).

TPU-native position (SURVEY.md §2.3): *in-mesh* communication is in-graph
XLA collectives over ICI and never goes through this API.  What remains is
out-of-graph coordination between CPU actors / separate meshes — host
numpy arrays moved through the object store via a named rendezvous actor.
The group/rendezvous shape matches the reference so ported code keeps
working; the NCCL communicator underneath is simply gone.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_LOCAL_GROUPS: Dict[str, "GroupHandle"] = {}

# Rendezvous timeout: failing loudly beats silently returning None.
TIMEOUT_S = 300.0


def _now() -> float:
    import time

    return time.monotonic()


@ray_tpu.remote
class _CollectiveGroupActor:
    """Rendezvous + reduction state for one group (the moral equivalent of
    the reference's NCCLUniqueIDStore named actor, util/collective/util.py:9)."""

    # A slot whose last touch is older than every possible waiter's timeout
    # window can have no live waiter left; it is garbage from an abandoned
    # round (some rank timed out and will never call back) and must be
    # evicted or the actor leaks a slot per timeout, forever.
    STALE_SLOT_GRACE_S = 60.0

    def __init__(self, world_size: int):
        import threading

        self.world_size = world_size
        self._round: Dict[str, dict] = {}
        self._cv = threading.Condition()

    def _slot(self, op_key: str):
        self._gc_stale_slots()
        if op_key not in self._round:
            self._round[op_key] = {"values": {}, "result": None, "done": 0,
                                   "last_touch": _now()}
        else:
            self._round[op_key]["last_touch"] = _now()
        return self._round[op_key]

    def _gc_stale_slots(self):
        """Evict *unfinished* slots untouched for longer than TIMEOUT_S +
        grace: every active waiter refreshed last_touch when it entered its
        wait and waits at most TIMEOUT_S, so such slots have no live
        waiters and the round can never complete.  Slots with a result are
        kept — put_value stores must serve arbitrarily late consumers
        (their cleanup is the expected_consumers count)."""
        ttl = TIMEOUT_S + self.STALE_SLOT_GRACE_S
        now = _now()
        for key in [k for k, s in self._round.items()
                    if s["result"] is None and now - s["last_touch"] > ttl]:
            self._round.pop(key, None)

    def contribute(self, op_key: str, rank: int, value, op: str):
        """Blocks until all ranks contribute; returns the reduced result.
        Raises TimeoutError if the group never completes the rendezvous —
        a silent None would poison every subsequent collective."""
        with self._cv:
            slot = self._slot(op_key)
            slot["values"][rank] = value
            if len(slot["values"]) == self.world_size:
                vals = [slot["values"][r] for r in range(self.world_size)]
                slot["result"] = _reduce(vals, op)
                self._cv.notify_all()
            elif not self._cv.wait_for(
                    lambda: slot["result"] is not None, timeout=TIMEOUT_S):
                # Leave the slot in place: other waiters hold references to
                # this dict, and a late arrival must still complete them.
                raise TimeoutError(
                    f"collective op {op_key!r} timed out after {TIMEOUT_S}s: "
                    f"{len(slot['values'])}/{self.world_size} ranks arrived")
            slot["done"] += 1
            result = slot["result"]
            if slot["done"] == self.world_size:
                self._round.pop(op_key, None)
            return result

    def put_value(self, key: str, value):
        with self._cv:
            self._slot(key)["result"] = value
            self._cv.notify_all()
        return True

    def get_value(self, key: str, expected_consumers: Optional[int] = None):
        with self._cv:
            slot = self._slot(key)
            if not self._cv.wait_for(
                    lambda: slot["result"] is not None, timeout=TIMEOUT_S):
                # Leave the slot: other consumers may still be inside their
                # own timeout windows and must see a late-arriving value.
                raise TimeoutError(
                    f"rendezvous for {key!r} timed out after {TIMEOUT_S}s")
            result = slot["result"]
            if expected_consumers is not None:
                slot["done"] += 1
                if slot["done"] >= expected_consumers:
                    self._round.pop(key, None)
            return result


def _reduce(vals: List[Any], op: str):
    if op == "SUM":
        return sum(vals[1:], vals[0])
    if op == "MAX":
        return np.maximum.reduce(vals)
    if op == "MIN":
        return np.minimum.reduce(vals)
    if op == "MEAN":
        return sum(vals[1:], vals[0]) / len(vals)
    if op == "GATHER":
        return list(vals)
    raise ValueError(f"bad reduce op {op}")


class GroupHandle:
    def __init__(self, name: str, world_size: int, rank: int, actor):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.actor = actor
        self._op_counter = 0
        # p2p sequence numbers are kept per (src, dst) *pair*: the global op
        # counter only advances on ops a rank participates in, so any
        # asymmetric send pattern (rank 0 -> 1 then 0 -> 2) would
        # permanently desynchronize sender and receiver keys.
        self._p2p_send: Dict[int, int] = {}
        self._p2p_recv: Dict[int, int] = {}

    def _next_key(self, op: str) -> str:
        self._op_counter += 1
        return f"{op}:{self._op_counter}"

    def _next_send_seq(self, dst_rank: int) -> int:
        self._p2p_send[dst_rank] = self._p2p_send.get(dst_rank, 0) + 1
        return self._p2p_send[dst_rank]

    def _next_recv_seq(self, src_rank: int) -> int:
        self._p2p_recv[src_rank] = self._p2p_recv.get(src_rank, 0) + 1
        return self._p2p_recv[src_rank]


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> GroupHandle:
    """Create/join a named group (reference: collective.py:120)."""
    actor_name = f"__collective__{group_name}"
    if rank == 0:
        # contribute() blocks in-actor until all ranks arrive, so the actor
        # needs one execution slot per rank.
        actor = _CollectiveGroupActor.options(
            name=actor_name, num_cpus=0,
            max_concurrency=world_size + 2).remote(world_size)
    else:
        import time

        deadline = time.monotonic() + 30
        while True:
            try:
                actor = ray_tpu.get_actor(actor_name)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
    handle = GroupHandle(group_name, world_size, rank, actor)
    _LOCAL_GROUPS[group_name] = handle
    return handle


def _group(group_name: str) -> GroupHandle:
    if group_name not in _LOCAL_GROUPS:
        raise ValueError(f"collective group {group_name!r} not initialized "
                         f"in this process")
    return _LOCAL_GROUPS[group_name]


def allreduce(tensor: np.ndarray, group_name: str = "default",
              op: str = "SUM") -> np.ndarray:
    g = _group(group_name)
    key = g._next_key("allreduce")
    return ray_tpu.get(g.actor.contribute.remote(key, g.rank,
                                                 np.asarray(tensor), op))


def allgather(tensor: np.ndarray, group_name: str = "default") -> List[np.ndarray]:
    g = _group(group_name)
    key = g._next_key("allgather")
    return ray_tpu.get(g.actor.contribute.remote(key, g.rank,
                                                 np.asarray(tensor), "GATHER"))


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "SUM"):
    out = allreduce(tensor, group_name, op)
    g = _group(group_name)
    return out if g.rank == dst_rank else tensor


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _group(group_name)
    key = g._next_key("broadcast")
    if g.rank == src_rank:
        ray_tpu.get(g.actor.put_value.remote(key, np.asarray(tensor)))
        return tensor
    return ray_tpu.get(g.actor.get_value.remote(key, g.world_size - 1))


def barrier(group_name: str = "default"):
    allreduce(np.zeros(1), group_name)


def send(tensor, dst_rank: int, group_name: str = "default"):
    g = _group(group_name)
    seq = g._next_send_seq(dst_rank)
    ray_tpu.get(g.actor.put_value.remote(
        f"p2p:{g.rank}->{dst_rank}:{seq}", np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default"):
    g = _group(group_name)
    seq = g._next_recv_seq(src_rank)
    return ray_tpu.get(g.actor.get_value.remote(
        f"p2p:{src_rank}->{g.rank}:{seq}", 1))
