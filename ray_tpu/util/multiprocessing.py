"""multiprocessing.Pool API over cluster tasks.

Reference: python/ray/util/multiprocessing/pool.py:276 (Pool mapping the
stdlib surface onto remote tasks).  Drop-in subset: apply/apply_async,
map/map_async, starmap, imap, imap_unordered, close/terminate/join, with
chunking so small work items amortize per-task overhead.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


@ray_tpu.remote
def _run_chunk(fn, chunk: List, star: bool):
    if star:
        return [fn(*args) for args in chunk]
    return [fn(x) for x in chunk]


class AsyncResult:
    def __init__(self, refs: List, chunked: bool = True):
        self._refs = refs
        self._chunked = chunked

    def get(self, timeout: Optional[float] = None):
        parts = ray_tpu.get(self._refs, timeout=timeout)
        if not self._chunked:
            return parts[0][0]
        return list(itertools.chain.from_iterable(parts))

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get()
            return True
        except Exception:
            return False


class Pool:
    """Stdlib-shaped process pool backed by the cluster scheduler; the
    `processes` count only bounds chunking (placement is the scheduler's
    job, matching the reference's semantics)."""

    def __init__(self, processes: Optional[int] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources()
                                   .get("CPU", 1)))
        self._procs = processes
        self._closed = False

    # ---- apply ----
    def apply(self, fn: Callable, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args=(), kwds=None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}
        ref = _run_chunk.remote(lambda: fn(*args, **kwds), [()], True)
        return AsyncResult([ref], chunked=False)

    # ---- map ----
    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._procs * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        refs = [_run_chunk.remote(fn, c, False)
                for c in self._chunks(iterable, chunksize)]
        return AsyncResult(refs)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_open()
        refs = [_run_chunk.remote(fn, c, True)
                for c in self._chunks(iterable, chunksize)]
        return AsyncResult(refs).get()

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1):
        self._check_open()
        refs = [_run_chunk.remote(fn, c, False)
                for c in self._chunks(iterable, chunksize)]
        for r in refs:
            yield from ray_tpu.get(r)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check_open()
        refs = [_run_chunk.remote(fn, c, False)
                for c in self._chunks(iterable, chunksize)]
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    # ---- lifecycle ----
    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.terminate()
