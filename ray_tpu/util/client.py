"""Ray-Client-style connection builder (reference: ray.client /
python/ray/client_builder.py — ``ray.client("ray://host:port").connect()``
returning a ClientContext usable as a context manager).

The transport underneath is the framework's native TCP remote-driver
plane (``ray_tpu.init(address=...)``), not a separate gRPC proxy: the
same control protocol the head speaks locally is what remote drivers
speak over the wire, so the "client" here is a thin, API-compatible
front on that — no second protocol to keep in sync."""
from __future__ import annotations

from typing import Optional


def normalize_address(address: str) -> str:
    """Strip the ``ray://`` client scheme; the single place this happens
    (init(address=...) and ClientBuilder both route through here)."""
    if address.startswith("ray://"):
        address = address[len("ray://"):]
    return address


class ClientContext:
    """What ``connect()`` returns; disconnecting (or leaving the ``with``
    block) tears down the remote-driver session."""

    def __init__(self, address: str):
        self.address = address

    def __enter__(self) -> "ClientContext":
        return self

    def __exit__(self, *exc) -> None:
        self.disconnect()

    def disconnect(self) -> None:
        import ray_tpu

        ray_tpu.shutdown()


class ClientBuilder:
    """Fluent builder: ``ray_tpu.client("ray://host:port")
    .env({"env_vars": {...}}).connect()``."""

    def __init__(self, address: str):
        self._address = normalize_address(address)
        self._runtime_env: Optional[dict] = None
        self._authkey: Optional[bytes] = None
        self._namespace: Optional[str] = None

    def env(self, runtime_env: dict) -> "ClientBuilder":
        self._runtime_env = runtime_env
        return self

    def namespace(self, namespace: str) -> "ClientBuilder":
        self._namespace = namespace
        return self

    def authkey(self, authkey: bytes) -> "ClientBuilder":
        """Not part of the reference surface: the reference's client
        server is unauthenticated inside the cluster perimeter; this
        plane requires the head's authkey (or RAY_TPU_AUTHKEY in the
        env)."""
        self._authkey = authkey
        return self

    def connect(self) -> ClientContext:
        import ray_tpu

        job_config = None
        if self._runtime_env or self._namespace:
            job_config = {}
            if self._runtime_env:
                job_config["runtime_env"] = self._runtime_env
            if self._namespace:
                job_config["namespace"] = self._namespace
        ray_tpu.init(address=self._address, _authkey=self._authkey,
                     job_config=job_config)
        return ClientContext(self._address)


def client(address: str) -> ClientBuilder:
    """Entry point (reference: ray.client(address))."""
    return ClientBuilder(address)
