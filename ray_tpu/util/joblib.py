"""joblib backend over cluster tasks.

Reference: python/ray/util/joblib/__init__.py (register_ray) +
ray_backend.py — a joblib ParallelBackend whose apply_async runs on the
cluster, so sklearn-style `with parallel_backend("ray_tpu"): ...` code
fans out without changes.
"""
from __future__ import annotations

import ray_tpu


@ray_tpu.remote
def _run_batch(batch):
    return batch()


def register_ray_tpu():
    """Register the 'ray_tpu' joblib parallel backend."""
    from joblib import register_parallel_backend
    from joblib._parallel_backends import ParallelBackendBase

    import threading

    class ImmediateResult:
        def __init__(self, ref):
            self._ref = ref

        def get(self, timeout=None):
            return ray_tpu.get(self._ref, timeout=timeout)

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        default_n_jobs = -1

        def configure(self, n_jobs=1, parallel=None, **kw):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            if n_jobs in (None, -1):
                if not ray_tpu.is_initialized():
                    ray_tpu.init()
                return max(1, int(ray_tpu.cluster_resources()
                                  .get("CPU", 1)))
            return n_jobs

        def apply_async(self, func, callback=None):
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            ref = _run_batch.remote(func)
            if callback is not None:
                # joblib's dispatch loop only hands out the next batch when
                # a completion callback fires (pre_dispatch batching) — run
                # it from a waiter thread like the pool backends do.
                def waiter():
                    try:
                        callback(ray_tpu.get(ref))
                    except Exception:
                        pass  # errors re-raise from .get() in retrieve()

                threading.Thread(target=waiter, daemon=True).start()
            return ImmediateResult(ref)

        def abort_everything(self, ensure_ready=True):
            pass

    register_parallel_backend("ray_tpu", RayTpuBackend)
