"""User metrics API (reference: python/ray/util/metrics.py Counter/Gauge/
Histogram on the C++ OpenCensus pipeline).  Here metrics aggregate in the
GCS KV under the "metrics" namespace; a Prometheus text endpoint can read
them out (dashboard round-2)."""
from __future__ import annotations

import pickle
from typing import Dict, Optional, Sequence, Tuple

from ray_tpu import internal_kv

_NS = "metrics"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> bytes:
        merged = {**self._default_tags, **(tags or {})}
        tag_str = ",".join(f"{k}={merged[k]}" for k in sorted(merged))
        return f"{self.name}|{tag_str}".encode()

    def _load(self, tags) -> float:
        raw = internal_kv.kv_get(self._key(tags), namespace=_NS)
        return pickle.loads(raw) if raw else 0.0

    def _store(self, tags, value):
        internal_kv.kv_put(self._key(tags), pickle.dumps(value), namespace=_NS)

    def value(self, tags: Optional[Dict[str, str]] = None):
        """Read the current recorded value for a tag set (0.0 when never
        recorded; a bucket-count list for Histogram).  Used by supervisors
        and tests to assert on counters, e.g. mesh_group_restarts_total."""
        return self._load(tags)


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        self._store(tags, self._load(tags) + value)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._store(tags, float(value))


class Meter(_Metric):
    """Counter for hot paths: ``mark()`` is pure in-process arithmetic and
    the GCS-KV write happens at most once per ``flush_interval`` seconds.
    A plain Counter pays one internal_kv round trip per inc(), which a
    per-fragment or per-step path cannot afford; a Meter amortizes that to
    ~0 while still surfacing through prometheus_text().  ``rate()`` reads
    the local events/second since creation (no kv traffic)."""

    kind = "meter"

    def __init__(self, name: str, description: str = "",
                 flush_interval: float = 2.0, tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        import time

        self.flush_interval = flush_interval
        self._pending = 0.0
        self._total = 0.0
        self._t0 = time.monotonic()
        self._last_flush = self._t0

    def mark(self, value: float = 1.0,
             tags: Optional[Dict[str, str]] = None):
        import time

        self._pending += value
        self._total += value
        now = time.monotonic()
        if now - self._last_flush >= self.flush_interval:
            self.flush(tags)

    def flush(self, tags: Optional[Dict[str, str]] = None):
        import time

        if self._pending:
            try:
                self._store(tags, self._load(tags) + self._pending)
                self._pending = 0.0
            except Exception:
                pass  # kv unavailable (driver shutting down): keep local
        self._last_flush = time.monotonic()

    def total(self) -> float:
        """Locally-observed total (includes unflushed marks)."""
        return self._total

    def rate(self) -> float:
        import time

        dt = time.monotonic() - self._t0
        return self._total / dt if dt > 0 else 0.0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (), tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        raw = internal_kv.kv_get(self._key(tags), namespace=_NS)
        counts = pickle.loads(raw) if raw else [0] * (len(self.boundaries) + 1)
        import bisect

        counts[bisect.bisect_left(self.boundaries, value)] += 1
        internal_kv.kv_put(self._key(tags), pickle.dumps(counts), namespace=_NS)


def prometheus_text() -> str:
    """Render all recorded metrics in Prometheus exposition format."""
    lines = []
    for key in internal_kv.kv_keys(b"", namespace=_NS):
        raw = internal_kv.kv_get(key, namespace=_NS)
        value = pickle.loads(raw)
        name, _, tag_str = key.decode().partition("|")
        labels = "{%s}" % ",".join(
            f'{p.split("=")[0]}="{p.split("=")[1]}"'
            for p in tag_str.split(",") if p) if tag_str else ""
        if isinstance(value, list):
            lines.append(f"{name}_count{labels} {sum(value)}")
        else:
            lines.append(f"{name}{labels} {value}")
    return "\n".join(lines) + "\n"
