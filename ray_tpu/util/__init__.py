"""Utility APIs (reference: python/ray/util/)."""
from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup,
    get_current_placement_group,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


def __getattr__(name):
    import importlib

    if name in ("collective", "actor_pool", "queue", "metrics", "iter",
                "multiprocessing", "joblib"):
        return importlib.import_module(f"ray_tpu.util.{name}")
    raise AttributeError(name)
