"""Placement groups (reference: python/ray/util/placement_group.py:128)."""
from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self, timeout: Optional[float] = None):
        """Block until all bundles are reserved (reference returns an
        ObjectRef; here a blocking call — wrap with .remote if needed)."""
        from ray_tpu._private.worker import global_worker

        return global_worker.transport.request(
            "pg_ready", {"pg_id": self.id, "timeout": timeout})

    def wait(self, timeout_seconds: float = 30) -> bool:
        try:
            self.ready(timeout=timeout_seconds)
            return True
        except Exception:
            return False

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    from ray_tpu._private.worker import global_worker

    if global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    pg_id = PlacementGroupID.from_random()
    pg = PlacementGroup(pg_id, bundles, strategy)
    # Fire the reservation; resolution is observed via pg.ready()/wait().
    import threading

    def create():
        try:
            global_worker.transport.request(
                "create_pg",
                {"pg_id": pg_id, "bundles": bundles, "strategy": strategy,
                 "name": name})
        except Exception:
            pass  # surfaced on ready()

    threading.Thread(target=create, daemon=True).start()
    return pg


def remove_placement_group(pg: PlacementGroup):
    from ray_tpu._private.worker import global_worker

    global_worker.transport.request("remove_pg", {"pg_id": pg.id})


def get_current_placement_group() -> Optional[PlacementGroup]:
    return None  # populated for tasks running inside a PG in a later round
