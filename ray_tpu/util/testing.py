"""Public test scaffolding (reference: the N18 mock/test layer —
src/mock/ray/* gmock headers, python/ray/_private/test_utils.py,
python/ray/cluster_utils.py — the pieces user test suites build on).

What the reference ships as C++ gmock interfaces dissolves here into a
small set of Python fakes and fixtures:

- ``local_cluster`` / ``remote_node_agents``: context managers for a
  fresh in-process cluster, optionally with real node-agent
  subprocesses (each its own host key → every cross-node object read
  exercises the TCP transfer plane).
- ``fake_tpu_env``: env-var dict for an N-device virtual CPU mesh (the
  JAX equivalent of the reference's _fake_gpus mode).
- ``TestConfig`` (re-export of ray_tpu.train.backend.TestConfig): the
  do-nothing Train backend for executor tests (reference:
  python/ray/train/tests/test_backend.py:45).
- ``wait_for_condition``: the reference's canonical poll helper
  (python/ray/_private/test_utils.py).
- ``inject_memory_pressure``: drive the memory monitor's test hook.
"""
from __future__ import annotations

import contextlib
import subprocess
import sys
import time
from typing import Callable, Dict, Iterator, Optional


def wait_for_condition(condition: Callable[[], bool], timeout: float = 30.0,
                       retry_interval_ms: float = 100.0) -> None:
    """Poll until `condition()` is truthy (reference:
    test_utils.wait_for_condition — same signature)."""
    deadline = time.monotonic() + timeout
    last_exc: Optional[BaseException] = None
    while time.monotonic() < deadline:
        try:
            if condition():
                return
            last_exc = None
        except Exception as e:  # noqa: BLE001 — condition may race startup
            last_exc = e
        time.sleep(retry_interval_ms / 1000.0)
    raise TimeoutError(
        f"condition not met within {timeout}s"
        + (f" (last error: {last_exc})" if last_exc else ""))


@contextlib.contextmanager
def local_cluster(num_cpus: float = 4, num_tpus: float = 0,
                  object_store_memory: int = 256 * 1024**2,
                  **init_kwargs) -> Iterator[object]:
    """Fresh single-process cluster, torn down on exit; yields the Head."""
    import ray_tpu

    ray_tpu.init(num_cpus=num_cpus, num_tpus=num_tpus,
                 object_store_memory=object_store_memory, **init_kwargs)
    try:
        yield ray_tpu._head
    finally:
        ray_tpu.shutdown()


def start_node_agent(head, num_cpus: int = 2,
                     resources: Optional[Dict[str, float]] = None,
                     store_capacity: int = 256 * 1024**2,
                     tpu_chips: int = 0) -> subprocess.Popen:
    """Spawn a real node-agent subprocess joined to `head` over TCP —
    a distinct host key, store, and worker pool (the multi-host test
    substrate; reference: ray.cluster_utils.Cluster.add_node)."""
    import json
    import os

    from ray_tpu._private import inject_pkg_pythonpath

    args = [sys.executable, "-m", "ray_tpu._private.node_agent",
            "--address", f"127.0.0.1:{head.tcp_port}",
            "--authkey", head.authkey.hex(),
            "--num-cpus", str(num_cpus),
            "--store-capacity", str(store_capacity)]
    if resources:
        args += ["--resources", json.dumps(resources)]
    if tpu_chips:
        args += ["--num-tpus", str(tpu_chips)]
    env = dict(os.environ)
    # The spawning process may have ray_tpu importable only via sys.path
    # (e.g. a driver script outside the repo) — make it explicit.
    inject_pkg_pythonpath(env)
    # Own session/process group: chaos.kill_node can SIGKILL the agent
    # AND every worker it spawned in one killpg (whole-node loss).
    return subprocess.Popen(args, env=env, start_new_session=True)


@contextlib.contextmanager
def remote_node_agents(head, n: int = 2, num_cpus: int = 2,
                       timeout: float = 30.0) -> Iterator[list]:
    """N node-agent subprocesses attached to `head`, reaped on exit."""
    baseline = len(head.raylets)  # capture before any agent can register
    agents = [start_node_agent(head, num_cpus=num_cpus) for _ in range(n)]
    try:
        wait_for_condition(
            lambda: len(head.raylets) >= baseline + n, timeout=timeout)
        yield agents
    finally:
        for a in agents:
            with contextlib.suppress(Exception):
                a.kill()
        for a in agents:  # reap: kill() alone leaves zombies
            with contextlib.suppress(Exception):
                a.wait(timeout=10)


def fake_tpu_env(n_devices: int = 8) -> Dict[str, str]:
    """Env overlay exposing an n-device virtual CPU mesh to a fresh
    python process (set BEFORE jax import; reference analogue:
    _fake_gpus, rllib/algorithms/algorithm_config.py:243)."""
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={n_devices}",
    }


def _test_config():
    from ray_tpu.train.backend import TestConfig

    return TestConfig


# Lazy import avoids pulling the Train stack in at module import; resolved
# on first attribute access below.
def __getattr__(name: str):
    if name == "TestConfig":
        return _test_config()
    raise AttributeError(name)


@contextlib.contextmanager
def inject_memory_pressure(tmp_dir: str, threshold: float = 0.9,
                           refresh_ms: int = 100) -> Iterator[Callable[[float], None]]:
    """Arrange (BEFORE init) for the memory monitor to read pressure from
    a file; yields `set_usage(fraction)`.  Restores flags on exit."""
    import os

    from ray_tpu._private.config import CONFIG

    gauge = os.path.join(tmp_dir, "memory_usage_gauge")

    def set_usage(fraction: float) -> None:
        with open(gauge, "w") as f:
            f.write(str(fraction))

    set_usage(0.0)
    saved = {k: os.environ.get(k) for k in
             ("RAY_TPU_MEMORY_MONITOR_TEST_FILE",
              "RAY_TPU_MEMORY_MONITOR_REFRESH_MS",
              "RAY_TPU_MEMORY_USAGE_THRESHOLD")}
    os.environ["RAY_TPU_MEMORY_MONITOR_TEST_FILE"] = gauge
    os.environ["RAY_TPU_MEMORY_MONITOR_REFRESH_MS"] = str(refresh_ms)
    os.environ["RAY_TPU_MEMORY_USAGE_THRESHOLD"] = str(threshold)
    CONFIG.reset()
    try:
        yield set_usage
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        CONFIG.reset()
