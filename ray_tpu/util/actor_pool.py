"""ActorPool (reference: python/ray/util/actor_pool.py)."""
from __future__ import annotations

from typing import Any, Callable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending: List[tuple] = []
        self._results: List[Any] = []

    def submit(self, fn: Callable, value: Any):
        if self._idle:
            actor = self._idle.pop()
            fut = fn(actor, value)
            self._future_to_actor[fut] = actor
        else:
            self._pending.append((fn, value))

    def get_next(self, timeout=None):
        if not self._future_to_actor:
            raise StopIteration("no pending work")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError
        fut = ready[0]
        actor = self._future_to_actor.pop(fut)
        if self._pending:
            fn, value = self._pending.pop(0)
            nfut = fn(actor, value)
            self._future_to_actor[nfut] = actor
        else:
            self._idle.append(actor)
        return ray_tpu.get(fut)

    def map(self, fn: Callable, values: List[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        for _ in range(len(values)):
            yield self.get_next()

    def has_next(self) -> bool:
        return bool(self._future_to_actor or self._pending)
