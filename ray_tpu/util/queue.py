"""Distributed queue on an actor (reference: python/ray/util/queue.py)."""
from __future__ import annotations

from typing import Any, Optional

import ray_tpu


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        import queue

        self.q = queue.Queue(maxsize=maxsize)

    def put(self, item, timeout=None):
        self.q.put(item, timeout=timeout)
        return True

    def get(self, timeout=None):
        return self.q.get(timeout=timeout)

    def qsize(self):
        return self.q.qsize()

    def empty(self):
        return self.q.empty()


class Queue:
    def __init__(self, maxsize: int = 0):
        self.actor = _QueueActor.options(
            num_cpus=0, max_concurrency=16).remote(maxsize)

    def put(self, item: Any, timeout: Optional[float] = None):
        ray_tpu.get(self.actor.put.remote(item, timeout))

    def get(self, timeout: Optional[float] = None) -> Any:
        return ray_tpu.get(self.actor.get.remote(timeout))

    def put_async(self, item: Any):
        return self.actor.put.remote(item, None)

    def get_async(self):
        return self.actor.get.remote(None)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())
