"""State API (reference: python/ray/experimental/state/api.py —
list_actors :736, list_nodes :827, list_tasks :959, list_objects :1003)."""
from __future__ import annotations

from typing import List


def _query(what: str) -> List[dict]:
    from ray_tpu import _worker

    return _worker().transport.request("state", {"what": what})


def list_actors() -> List[dict]:
    return _query("actors")


def list_nodes() -> List[dict]:
    return _query("nodes")


def list_tasks() -> List[dict]:
    return _query("tasks")


def list_objects() -> List[dict]:
    return _query("objects")


def list_jobs() -> List[dict]:
    return _query("jobs")


def list_named_actors(all_namespaces: bool = False) -> List[dict]:
    return _query("named_actors")


def summarize_tasks() -> dict:
    tasks = list_tasks()
    by_status: dict = {}
    for t in tasks:
        by_status.setdefault(t["status"], 0)
        by_status[t["status"]] += 1
    return {"total": len(tasks), "by_status": by_status}


def summarize_actors() -> dict:
    actors = list_actors()
    by_state: dict = {}
    for a in actors:
        by_state.setdefault(a["state"], 0)
        by_state[a["state"]] += 1
    return {"total": len(actors), "by_state": by_state}


def summarize_objects() -> dict:
    objs = list_objects()
    return {"total": len(objs), "total_bytes": sum(o["size"] for o in objs)}


# ---- tracing plane (see ray_tpu.observability) ----
def list_traces(limit: int = 50) -> List[dict]:
    """Traces the head's TraceStore currently holds, biggest first."""
    from ray_tpu import _worker

    return _worker().transport.request("traces", {"limit": limit})


def get_timeline(trace_id: str | None = None) -> dict:
    """Raw timeline material for one trace (or everything): task rows +
    spans.  ``ray_tpu.timeline()`` assembles the chrome dump from this."""
    from ray_tpu import _worker

    return _worker().transport.request("trace_timeline",
                                       {"trace_id": trace_id})


def summarize_spans() -> dict:
    """Per-span-family counts/seconds plus TraceStore budget stats."""
    from ray_tpu import _worker

    return _worker().transport.request("span_summary", {})
