"""Autoscaler: resource-demand-driven node scaling.

Reference: python/ray/autoscaler/_private/autoscaler.py:168
(StandardAutoscaler.update :366 — read load metrics, bin-pack pending
demands onto node types, ask the NodeProvider to launch/terminate) and the
FakeMultiNodeProvider test provider (fake_multi_node/node_provider.py:237).

The TPU deployment unit is a *slice* (a whole pod-slice of hosts joins or
leaves together), so node types here are slice-shaped bundles.  The
in-process provider adds/removes virtual raylets — the same mechanism the
reference uses for autoscaler tests.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class NodeProvider:
    """Pluggable provider interface (reference: autoscaler/node_provider.py:13)."""

    def create_node(self, node_type: str, resources: Dict[str, float]):
        raise NotImplementedError

    def terminate_node(self, node_id):
        raise NotImplementedError

    def non_terminated_nodes(self) -> List:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Adds/removes virtual raylets in the running head (the fake-multinode
    pattern)."""

    def __init__(self, head=None):
        import ray_tpu

        self.head = head or ray_tpu._global_head()
        self.created: List = []

    def create_node(self, node_type: str, resources: Dict[str, float]):
        node_id = self.head.add_node(resources, labels={"node_type": node_type})
        self.created.append(node_id)
        return node_id

    def terminate_node(self, node_id):
        self.head.remove_node(node_id)
        if node_id in self.created:
            self.created.remove(node_id)

    def non_terminated_nodes(self) -> List:
        return list(self.created)


class StandardAutoscaler:
    def __init__(self, node_types: Dict[str, Dict],
                 provider: Optional[NodeProvider] = None,
                 max_nodes: int = 8, idle_timeout_s: float = 60.0,
                 head=None):
        """node_types: {name: {"resources": {...}, "max_workers": n}}."""
        import ray_tpu

        self.head = head or ray_tpu._global_head()
        self.provider = provider or LocalNodeProvider(self.head)
        self.node_types = node_types
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self._node_idle_since: Dict = {}

    # ---- one reconciliation pass (reference: update :366) ----
    def update(self) -> Dict[str, int]:
        launched: Dict[str, int] = {}
        demands = self._pending_demands()
        for demand in demands:
            if len(self.provider.non_terminated_nodes()) >= self.max_nodes:
                break
            nt = self._fit_node_type(demand)
            if nt is not None:
                self.provider.create_node(nt, dict(
                    self.node_types[nt]["resources"]))
                launched[nt] = launched.get(nt, 0) + 1
        self._terminate_idle()
        return launched

    def _pending_demands(self) -> List[Dict[str, float]]:
        with self.head._lock:
            demands = [dict(spec.resources) for spec in self.head.pending]
            for raylet in self.head.raylets.values():
                demands.extend(dict(s.resources) for s in raylet.queued)
            # Pending placement groups contribute bundle demands.
            for pg in self.head._pending_pgs:
                demands.extend(dict(b.resources) for b in pg.bundles)
        return demands

    def _fit_node_type(self, demand: Dict[str, float]) -> Optional[str]:
        for name, nt in self.node_types.items():
            res = nt["resources"]
            if all(res.get(k, 0.0) >= v for k, v in demand.items()):
                count = sum(1 for n in self.provider.non_terminated_nodes())
                if count < nt.get("max_workers", self.max_nodes):
                    return name
        return None

    def _terminate_idle(self):
        now = time.monotonic()
        for node_id in list(self.provider.non_terminated_nodes()):
            raylet = self.head.raylets.get(node_id)
            if raylet is None:
                continue
            busy = (raylet.queued
                    or any(w.busy or w.actor_id for w in raylet.workers.values()))
            if busy:
                self._node_idle_since.pop(node_id, None)
                continue
            since = self._node_idle_since.setdefault(node_id, now)
            if now - since > self.idle_timeout_s:
                self.provider.terminate_node(node_id)
                self._node_idle_since.pop(node_id, None)


class Monitor:
    """Background loop hosting the autoscaler (reference:
    autoscaler/_private/monitor.py:126)."""

    def __init__(self, autoscaler: StandardAutoscaler, interval_s: float = 1.0):
        self.autoscaler = autoscaler
        self.interval = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-autoscaler")

    def start(self):
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                pass
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()
