"""Autoscaler: resource-demand-driven node scaling.

Reference: python/ray/autoscaler/_private/autoscaler.py:168
(StandardAutoscaler.update :366 — read load metrics, bin-pack pending
demands onto node types, ask the NodeProvider to launch/terminate) and the
FakeMultiNodeProvider test provider (fake_multi_node/node_provider.py:237).

The TPU deployment unit is a *slice* (a whole pod-slice of hosts joins or
leaves together), so node types here are slice-shaped bundles.  The
in-process provider adds/removes virtual raylets — the same mechanism the
reference uses for autoscaler tests.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class NodeProvider:
    """Pluggable provider interface (reference: autoscaler/node_provider.py:13)."""

    def create_node(self, node_type: str, resources: Dict[str, float]):
        raise NotImplementedError

    def terminate_node(self, node_id):
        raise NotImplementedError

    def non_terminated_nodes(self) -> List:
        raise NotImplementedError

    def node_type_counts(self) -> Dict[str, int]:
        return {}


class LocalNodeProvider(NodeProvider):
    """Adds/removes virtual raylets in the running head (the fake-multinode
    pattern)."""

    def __init__(self, head=None):
        import ray_tpu

        self.head = head or ray_tpu._global_head()
        self.created: List = []
        self._types: Dict = {}

    def create_node(self, node_type: str, resources: Dict[str, float]):
        node_id = self.head.add_node(resources, labels={"node_type": node_type})
        self.created.append(node_id)
        self._types[node_id] = node_type
        return node_id

    def terminate_node(self, node_id):
        self.head.remove_node(node_id)
        if node_id in self.created:
            self.created.remove(node_id)
        self._types.pop(node_id, None)

    def non_terminated_nodes(self) -> List:
        return list(self.created)

    def node_type_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for t in self._types.values():
            counts[t] = counts.get(t, 0) + 1
        return counts


class FakeMultiNodeProvider(NodeProvider):
    """Launches REAL node-agent subprocesses (reference: the fake-
    multinode provider that starts actual raylets on one machine,
    autoscaler/_private/fake_multi_node/node_provider.py:237).  Each
    launch carries a unique token resource so the provider can bind the
    subprocess to the node id the head assigns when the agent
    registers."""

    def __init__(self, head=None, register_timeout_s: float = 30.0):
        import ray_tpu

        self.head = head or ray_tpu._global_head()
        self.register_timeout_s = register_timeout_s
        self._procs: Dict = {}     # node_id -> subprocess
        self._types: Dict = {}     # node_id -> node_type
        self._counter = 0

    def create_node(self, node_type: str, resources: Dict[str, float]):
        import json as _json
        import subprocess
        import sys
        import time as _time

        self._counter += 1
        token = f"_launch_{self._counter}"
        res = dict(resources)
        cpus = res.pop("CPU", 1)
        res[token] = 1.0
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_agent",
             "--address", f"127.0.0.1:{self.head.tcp_port}",
             "--authkey", self.head.authkey.hex(),
             "--num-cpus", str(int(cpus)),
             "--resources", _json.dumps(res),
             "--store-capacity", str(128 * 1024 * 1024)])
        deadline = _time.monotonic() + self.register_timeout_s
        while _time.monotonic() < deadline:
            for node_id, nres in list(self.head.scheduler.nodes.items()):
                if nres.total.get(token):
                    self._procs[node_id] = proc
                    self._types[node_id] = node_type
                    return node_id
            _time.sleep(0.1)
        proc.kill()
        raise TimeoutError(f"node of type {node_type!r} never registered")

    def terminate_node(self, node_id):
        proc = self._procs.pop(node_id, None)
        self._types.pop(node_id, None)
        self.head.remove_node(node_id)
        if proc is not None:
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except Exception:
                proc.kill()

    def non_terminated_nodes(self) -> List:
        return [n for n, p in self._procs.items() if p.poll() is None]

    def node_type_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for n, t in self._types.items():
            if n in self._procs and self._procs[n].poll() is None:
                counts[t] = counts.get(t, 0) + 1
        return counts

    def shutdown(self):
        for node_id in list(self._procs):
            self.terminate_node(node_id)


class TrainingGangPolicy:
    """Scale decision for ONE elastic training gang (an ElasticMeshGroup
    or anything duck-typed like it: ``hosts`` attribute, ``pending_steps()``
    and ``request_resize(n)`` methods).

    Gangs scale as a unit, so the generic bin-packing loop can't drive
    them — a gang never wants "one more node somewhere", it wants "resize
    the whole gang to N".  The policy maps spare cluster capacity plus the
    gang's own backlog onto a target size: grow only when work is actually
    queued (``pending_steps() >= scale_threshold``) and spare hosts exist;
    never propose below ``min_hosts`` (preemption handling, not this
    policy, shrinks the gang)."""

    def __init__(self, controller, min_hosts: int, max_hosts: int,
                 scale_threshold: int = 1):
        self.controller = controller
        self.min_hosts = int(min_hosts)
        self.max_hosts = int(max_hosts)
        self.scale_threshold = int(scale_threshold)

    def desired(self, spare_hosts: int) -> int:
        cur = int(self.controller.hosts)
        pending = int(self.controller.pending_steps())
        target = cur
        if pending >= self.scale_threshold and spare_hosts > 0:
            target = min(self.max_hosts, cur + spare_hosts)
        return max(self.min_hosts, target)

    def apply(self, spare_hosts: int) -> Optional[int]:
        """Returns the requested size when a resize was proposed."""
        target = self.desired(spare_hosts)
        if target != int(self.controller.hosts):
            self.controller.request_resize(target)
            return target
        return None


class StandardAutoscaler:
    def __init__(self, node_types: Dict[str, Dict],
                 provider: Optional[NodeProvider] = None,
                 max_nodes: int = 8, idle_timeout_s: float = 60.0,
                 head=None):
        """node_types: {name: {"resources": {...}, "max_workers": n}}."""
        import ray_tpu

        self.head = head or ray_tpu._global_head()
        self.provider = provider or LocalNodeProvider(self.head)
        self.node_types = node_types
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self._node_idle_since: Dict = {}
        self._gang_policies: List[TrainingGangPolicy] = []
        # Register the launchable shapes with the scheduler so demands
        # only a future node can satisfy stay PENDING (for this loop to
        # serve) instead of erroring as infeasible at submit.  (Like the
        # reference, a demand that fits a node type but exhausts the
        # launch budget waits pending rather than erroring.)  detach()
        # restores strict feasibility when the autoscaler stops.
        self.head.scheduler.external_capacity = [
            dict(nt["resources"]) for nt in node_types.values()]

    def detach(self):
        """Stop advertising launchable capacity: without a live monitor,
        a pending-forever demand should raise Infeasible at submit."""
        self.head.scheduler.external_capacity = []

    def register_gang_policy(self, policy: "TrainingGangPolicy"):
        """Let update() drive an elastic training gang's size alongside
        node scaling.  Returns the policy so callers can unregister it."""
        self._gang_policies.append(policy)
        return policy

    def unregister_gang_policy(self, policy: "TrainingGangPolicy"):
        if policy in self._gang_policies:
            self._gang_policies.remove(policy)

    # ---- one reconciliation pass (reference: update :366 + the
    # resource_demand_scheduler bin-packing) ----
    def update(self) -> Dict[str, int]:
        demands = self._pending_demands()
        # 1) Existing nodes absorb what fits into their free capacity —
        #    a queued task the cluster can already run must not launch a
        #    node.  Anti-affinity demands (STRICT_SPREAD bundles) sharing
        #    a key must land on DISTINCT nodes.
        with self.head._lock:
            free = [[dict(n.available), set()]
                    for n in self.head.scheduler.nodes.values()]
        unmet = []
        for d, key in demands:
            if not d:
                continue
            placed = False
            for f, keys in free:
                if key is not None and key in keys:
                    continue
                if all(f.get(k, 0.0) >= v for k, v in d.items()):
                    for k, v in d.items():
                        f[k] = f.get(k, 0.0) - v
                    if key is not None:
                        keys.add(key)
                    placed = True
                    break
            if not placed:
                unmet.append((d, key))
        # 2) First-fit-decreasing pack of the remainder onto virtual new
        #    nodes: one launched node serves MANY demands (the reference
        #    packs demands per node type before asking the provider).
        planned: List[list] = []  # [node_type, remaining, anti_keys]
        budget = self.max_nodes - len(self.provider.non_terminated_nodes())
        per_type = {name: 0 for name in self.node_types}
        for d, key in sorted(unmet, key=lambda dk: -sum(dk[0].values())):
            placed = False
            for plan in planned:
                _nt, rem, keys = plan
                if key is not None and key in keys:
                    continue
                if all(rem.get(k, 0.0) >= v for k, v in d.items()):
                    for k, v in d.items():
                        rem[k] = rem.get(k, 0.0) - v
                    if key is not None:
                        keys.add(key)
                    placed = True
                    break
            if placed or len(planned) >= max(0, budget):
                continue
            nt = self._fit_node_type(d, per_type)
            if nt is None:
                continue
            rem = dict(self.node_types[nt]["resources"])
            for k, v in d.items():
                rem[k] = rem.get(k, 0.0) - v
            planned.append([nt, rem, {key} if key is not None else set()])
            per_type[nt] += 1
        launched: Dict[str, int] = {}
        for nt, _rem, _keys in planned:
            self.provider.create_node(nt, dict(
                self.node_types[nt]["resources"]))
            launched[nt] = launched.get(nt, 0) + 1
        self._terminate_idle()
        # 3) Offer whatever launch budget is left to registered training
        #    gangs: gangs resize as a unit through their own controller
        #    (the resize happens at the gang's next step boundary, not
        #    here), so the only coupling is the spare-capacity signal.
        if self._gang_policies:
            spare = max(0, self.max_nodes
                        - len(self.provider.non_terminated_nodes()))
            for policy in list(self._gang_policies):
                try:
                    policy.apply(spare)
                except Exception:
                    # A dead/shutdown gang must not wedge the scaling loop.
                    pass
        return launched

    def _pending_demands(self) -> List[tuple]:
        """Pending (resources, anti_affinity_key) pairs.  The key is set
        for STRICT_SPREAD placement-group bundles: two demands sharing a
        key must NOT count against one node's capacity (free absorption
        or planned-node packing) — they need distinct nodes."""
        with self.head._lock:
            # head.pending = demands NO node could place (the scale-up
            # signal).  Tasks queued at a raylet already hold allocated
            # resources there (waiting on a worker slot), so counting
            # them would double-book demand against capacity.
            demands = [(dict(spec.resources), None)
                       for spec in self.head.pending]
            # Pending placement groups contribute bundle demands.
            for pg in self.head._pending_pgs:
                strict = getattr(pg, "strategy", "") == "STRICT_SPREAD"
                key = pg.pg_id if strict else None
                demands.extend((dict(b.resources), key)
                           for b in pg.bundles if b.node_id is None)
        return demands

    def _fit_node_type(self, demand: Dict[str, float],
                       planned_per_type: Optional[Dict[str, int]] = None
                       ) -> Optional[str]:
        """Smallest node type whose resources cover the demand, honoring
        per-type max_workers (existing + planned this pass)."""
        planned_per_type = planned_per_type or {}
        existing_per_type = self.provider.node_type_counts()
        live = self.provider.non_terminated_nodes()
        if not existing_per_type and live:
            # Provider without per-type accounting (base default {}):
            # fall back to the conservative total-count bound so
            # max_workers can never be silently exceeded.
            existing_per_type = {name: len(live) for name in self.node_types}
        candidates = []
        for name, nt in self.node_types.items():
            res = nt["resources"]
            if not all(res.get(k, 0.0) >= v for k, v in demand.items()):
                continue
            count = (existing_per_type.get(name, 0)
                     + planned_per_type.get(name, 0))
            if count >= nt.get("max_workers", self.max_nodes):
                continue
            # Tightest fit ON THE DEMANDED resources: summing raw units
            # would let a GB-scale resource (memory) dominate and pick a
            # grossly oversized node for a 1-CPU demand.
            overprovision = sum(res.get(k, 0.0) / v
                                for k, v in demand.items() if v > 0)
            candidates.append((overprovision, name))
        return min(candidates)[1] if candidates else None

    def _terminate_idle(self):
        now = time.monotonic()
        for node_id in list(self.provider.non_terminated_nodes()):
            raylet = self.head.raylets.get(node_id)
            if raylet is None:
                continue
            busy = (raylet.queued
                    or any(w.busy or w.actor_id for w in raylet.workers.values()))
            if busy:
                self._node_idle_since.pop(node_id, None)
                continue
            since = self._node_idle_since.setdefault(node_id, now)
            if now - since > self.idle_timeout_s:
                self.provider.terminate_node(node_id)
                self._node_idle_since.pop(node_id, None)


class Monitor:
    """Background loop hosting the autoscaler (reference:
    autoscaler/_private/monitor.py:126)."""

    def __init__(self, autoscaler: StandardAutoscaler, interval_s: float = 1.0):
        self.autoscaler = autoscaler
        self.interval = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-autoscaler")

    def start(self):
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                pass
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()
        self.autoscaler.detach()
