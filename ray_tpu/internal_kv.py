"""Internal KV store on the GCS (reference: ray.experimental.internal_kv)."""
from __future__ import annotations

from typing import List, Optional


def _req(payload: dict):
    from ray_tpu import _worker

    return _worker().transport.request("kv", payload)


def kv_put(key: bytes, value: bytes, overwrite: bool = True,
           namespace: str = "default") -> bool:
    return _req({"verb": "put", "key": key, "value": value,
                 "overwrite": overwrite, "namespace": namespace})


def kv_get(key: bytes, namespace: str = "default") -> Optional[bytes]:
    return _req({"verb": "get", "key": key, "namespace": namespace})


def kv_del(key: bytes, namespace: str = "default"):
    return _req({"verb": "del", "key": key, "namespace": namespace})


def kv_keys(prefix: bytes = b"", namespace: str = "default") -> List[bytes]:
    return _req({"verb": "keys", "prefix": prefix, "namespace": namespace})
