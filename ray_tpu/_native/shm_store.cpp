// Plasma-style shared-memory arena store.
//
// TPU-native equivalent of the reference's plasma core (src/ray/object_manager/
// plasma/store.h:55, dlmalloc.cc over mmap, object_lifecycle_manager.h):
// one shm segment per store, a first-fit free-list allocator with boundary
// coalescing, and an object index (id -> offset/size/sealed).  The head
// process owns allocation; readers in any process mmap the same segment
// (/dev/shm/<name>) and take zero-copy views at the returned offsets.
//
// Exposed as a C ABI for ctypes (the image has no pybind11).  All exported
// functions are thread-safe via a per-store mutex.
#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr size_t kAlign = 64;  // cache-line alignment for numpy views

inline size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

struct ObjectEntry {
  size_t offset;
  size_t size;
  bool sealed;
  std::string metadata;
};

struct Store {
  int fd = -1;
  uint8_t* base = nullptr;
  size_t capacity = 0;
  std::string name;
  std::mutex mu;
  // Free list: offset -> size (ordered, for coalescing).
  std::map<size_t, size_t> free_by_offset;
  std::unordered_map<std::string, ObjectEntry> objects;
  std::atomic<size_t> used{0};

  ~Store() {
    if (base) munmap(base, capacity);
    if (fd >= 0) close(fd);
    if (!name.empty()) shm_unlink(name.c_str());
  }

  int64_t allocate(size_t size) {
    size = align_up(size ? size : 1);
    // First fit.
    for (auto it = free_by_offset.begin(); it != free_by_offset.end(); ++it) {
      if (it->second >= size) {
        size_t off = it->first;
        size_t remaining = it->second - size;
        free_by_offset.erase(it);
        if (remaining > 0) free_by_offset[off + size] = remaining;
        used += size;
        return static_cast<int64_t>(off);
      }
    }
    return -1;
  }

  void release(size_t offset, size_t size) {
    size = align_up(size ? size : 1);
    used -= size;
    auto next = free_by_offset.lower_bound(offset);
    // Coalesce with previous block.
    if (next != free_by_offset.begin()) {
      auto prev = std::prev(next);
      if (prev->first + prev->second == offset) {
        offset = prev->first;
        size += prev->second;
        free_by_offset.erase(prev);
      }
    }
    // Coalesce with next block.
    if (next != free_by_offset.end() && offset + size == next->first) {
      size += next->second;
      free_by_offset.erase(next);
    }
    free_by_offset[offset] = size;
  }
};

std::string id_key(const uint8_t* id, int id_len) {
  return std::string(reinterpret_cast<const char*>(id), id_len);
}

}  // namespace

extern "C" {

// Returns an opaque handle (owner side creates the segment).
void* rtpu_store_create(const char* name, uint64_t capacity) {
  auto* s = new Store();
  s->name = name;
  shm_unlink(name);  // stale segment from a crashed run
  s->fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (s->fd < 0) { delete s; return nullptr; }
  if (ftruncate(s->fd, static_cast<off_t>(capacity)) != 0) {
    delete s; return nullptr;
  }
  s->base = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                       PROT_READ | PROT_WRITE, MAP_SHARED,
                                       s->fd, 0));
  if (s->base == MAP_FAILED) { s->base = nullptr; delete s; return nullptr; }
  s->capacity = capacity;
  s->free_by_offset[0] = capacity;
  return s;
}

void rtpu_store_destroy(void* handle) {
  delete static_cast<Store*>(handle);
}

// Allocate space for an object; returns offset or -1 (full / exists).
int64_t rtpu_store_allocate(void* handle, const uint8_t* id, int id_len,
                            uint64_t size) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  auto key = id_key(id, id_len);
  if (s->objects.count(key)) return -1;
  int64_t off = s->allocate(size);
  if (off < 0) return -1;
  s->objects[key] = ObjectEntry{static_cast<size_t>(off), size, false, {}};
  return off;
}

int rtpu_store_seal(void* handle, const uint8_t* id, int id_len,
                    const uint8_t* meta, int meta_len) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->objects.find(id_key(id, id_len));
  if (it == s->objects.end()) return -1;
  it->second.metadata.assign(reinterpret_cast<const char*>(meta), meta_len);
  it->second.sealed = true;
  return 0;
}

// Lookup: returns offset or -1; fills size and metadata length.
int64_t rtpu_store_get(void* handle, const uint8_t* id, int id_len,
                       uint64_t* size_out, int* meta_len_out) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->objects.find(id_key(id, id_len));
  if (it == s->objects.end() || !it->second.sealed) return -1;
  *size_out = it->second.size;
  *meta_len_out = static_cast<int>(it->second.metadata.size());
  return static_cast<int64_t>(it->second.offset);
}

int rtpu_store_get_meta(void* handle, const uint8_t* id, int id_len,
                        uint8_t* meta_buf, int meta_buf_len) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->objects.find(id_key(id, id_len));
  if (it == s->objects.end()) return -1;
  int n = static_cast<int>(it->second.metadata.size());
  if (n > meta_buf_len) return -1;
  std::memcpy(meta_buf, it->second.metadata.data(), n);
  return n;
}

int rtpu_store_delete(void* handle, const uint8_t* id, int id_len) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->objects.find(id_key(id, id_len));
  if (it == s->objects.end()) return -1;
  s->release(it->second.offset, it->second.size);
  s->objects.erase(it);
  return 0;
}

uint64_t rtpu_store_used(void* handle) {
  return static_cast<Store*>(handle)->used.load();
}

uint64_t rtpu_store_num_objects(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->objects.size();
}

}  // extern "C"
