"""Native (C++) components, built on demand with g++ and bound via ctypes.

Currently: the shared-memory arena store (shm_store.cpp) — the plasma-core
equivalent.  Falls back gracefully (callers check `available()`)."""
from __future__ import annotations

import ctypes
import mmap as mmap_mod
import os
import subprocess
import threading
from typing import Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libshm_store.so")
_SRC = os.path.join(_HERE, "shm_store.cpp")

_lib = None
_build_lock = threading.Lock()
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    with _build_lock:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            try:
                return ctypes.CDLL(_SO)
            except OSError:
                # A stale binary built against a different glibc/toolchain
                # (e.g. checked out on an older container) must not break
                # the graceful fallback — rebuild from source below.
                pass
        if _build_failed:
            return None
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
                 "-o", _SO, "-lrt"],
                check=True, capture_output=True, timeout=120)
            return ctypes.CDLL(_SO)
        except Exception:
            _build_failed = True
            return None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None:
        lib = _build()
        if lib is None:
            return None
        lib.rtpu_store_create.restype = ctypes.c_void_p
        lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_store_destroy.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_allocate.restype = ctypes.c_int64
        lib.rtpu_store_allocate.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64]
        lib.rtpu_store_seal.restype = ctypes.c_int
        lib.rtpu_store_seal.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int]
        lib.rtpu_store_get.restype = ctypes.c_int64
        lib.rtpu_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int)]
        lib.rtpu_store_get_meta.restype = ctypes.c_int
        lib.rtpu_store_get_meta.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int]
        lib.rtpu_store_delete.restype = ctypes.c_int
        lib.rtpu_store_delete.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.rtpu_store_used.restype = ctypes.c_uint64
        lib.rtpu_store_used.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_num_objects.restype = ctypes.c_uint64
        lib.rtpu_store_num_objects.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def available() -> bool:
    return _get_lib() is not None


class NativeArenaStore:
    """Owner-side handle (lives in the head process)."""

    def __init__(self, name: str, capacity: int):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native store unavailable (g++ build failed)")
        self._lib = lib
        self.name = name
        self.capacity = capacity
        self._handle = lib.rtpu_store_create(name.encode(), capacity)
        if not self._handle:
            raise RuntimeError(f"failed to create native store {name!r}")
        # Owner-side view over the whole arena for zero-copy writes.
        fd = os.open(f"/dev/shm/{name}", os.O_RDWR)
        try:
            self._map = mmap_mod.mmap(fd, capacity)
        finally:
            os.close(fd)

    def allocate(self, object_id: bytes, size: int) -> Optional[memoryview]:
        off = self._lib.rtpu_store_allocate(self._handle, object_id,
                                            len(object_id), size)
        if off < 0:
            return None
        return memoryview(self._map)[off: off + size]

    def seal(self, object_id: bytes, metadata: bytes):
        rc = self._lib.rtpu_store_seal(self._handle, object_id,
                                       len(object_id), metadata, len(metadata))
        if rc != 0:
            raise KeyError(f"seal: unknown object {object_id.hex()}")

    def lookup(self, object_id: bytes) -> Optional[Tuple[int, int, bytes]]:
        """Returns (offset, size, metadata) for sealed objects, else None."""
        size = ctypes.c_uint64()
        meta_len = ctypes.c_int()
        off = self._lib.rtpu_store_get(self._handle, object_id,
                                       len(object_id),
                                       ctypes.byref(size),
                                       ctypes.byref(meta_len))
        if off < 0:
            return None
        buf = ctypes.create_string_buffer(meta_len.value)
        self._lib.rtpu_store_get_meta(self._handle, object_id, len(object_id),
                                      ctypes.cast(buf, ctypes.c_char_p),
                                      meta_len.value)
        return int(off), int(size.value), buf.raw

    def view(self, offset: int, size: int) -> memoryview:
        return memoryview(self._map)[offset: offset + size]

    def delete(self, object_id: bytes) -> bool:
        return self._lib.rtpu_store_delete(self._handle, object_id,
                                           len(object_id)) == 0

    @property
    def used(self) -> int:
        return int(self._lib.rtpu_store_used(self._handle))

    @property
    def num_objects(self) -> int:
        return int(self._lib.rtpu_store_num_objects(self._handle))

    def close(self):
        if self._handle:
            try:
                self._map.close()
            except Exception:
                pass
            self._lib.rtpu_store_destroy(self._handle)
            self._handle = None


class ArenaReader:
    """Reader-side attach (worker processes): mmap the arena read-only."""

    _cache: dict = {}
    _lock = threading.Lock()

    @classmethod
    def view(cls, store_name: str, offset: int, size: int,
             capacity: int) -> memoryview:
        with cls._lock:
            m = cls._cache.get(store_name)
            if m is None:
                fd = os.open(f"/dev/shm/{store_name}", os.O_RDONLY)
                try:
                    m = mmap_mod.mmap(fd, capacity, prot=mmap_mod.PROT_READ)
                finally:
                    os.close(fd)
                cls._cache[store_name] = m
        return memoryview(m)[offset: offset + size]
