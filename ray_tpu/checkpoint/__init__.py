"""Distributed sharded async checkpointing.

Each rank of a gang snapshots its LOCAL pytree shard to host memory (a
bounded pause, off the device step path) and persists it in the background
into a shared store; a two-phase commit — per-rank shard files first, then
one atomic ``MANIFEST.json`` rename — guarantees a reader never observes a
partial checkpoint.  Chunked content addressing dedups unchanged state
across consecutive saves, and per-array ``global_shape``/``index`` metadata
lets an N-rank checkpoint restore onto an M-rank gang (elastic resize).

Store layout (one directory tree, typically on shared storage)::

    <root>/
      chunks/<hh>/<hash>            content-addressed chunk store
      steps/step_<NNNNNNNN>/
          rank_<RRRRR>.json         per-rank shard metadata (phase 1)
          checkpoint.pkl            (dict-kind checkpoints only)
          MANIFEST.json             atomic commit marker (phase 2)

A checkpoint EXISTS iff its ``MANIFEST.json`` exists; shard files without a
manifest are an aborted save, garbage-collected by the next committed one.

See docs/CHECKPOINTING.md for the commit protocol, dedup knobs and
resharding semantics.
"""
from ray_tpu.checkpoint.chunks import ChunkStore, default_chunk_bytes  # noqa: F401
from ray_tpu.checkpoint.manifest import (  # noqa: F401
    commit_manifest,
    committed_steps,
    evict_steps,
    gc_chunks,
    gc_orphans,
    latest_committed_step,
    read_manifest,
    step_dir,
)
from ray_tpu.checkpoint.saver import (  # noqa: F401
    ShardWriter,
    persist_dict_checkpoint,
    save_tree,
)
from ray_tpu.checkpoint.restore import (  # noqa: F401
    assemble_arrays,
    restore_tree,
)
from ray_tpu.checkpoint.tree import (  # noqa: F401
    axis0_restore_index,
    axis0_shard_index,
    flatten_with_paths,
    unflatten_like,
)
from ray_tpu.checkpoint.coordinator import (  # noqa: F401
    AsyncCommitter,
    DistributedCheckpointer,
    commit_when_complete,
)
