"""Per-rank shard persistence: bounded-pause snapshot + background write.

``ShardWriter`` runs INSIDE a rank's process.  ``snapshot()`` is the only
piece on the step path — one batched device→host fetch of the local shard
(the bounded pause; nothing else blocks the device).  ``persist()`` /
``persist_async()`` then chunk, hash and write the snapshot into the
content-addressed store and drop the rank's shard-metadata file — phase 1
of the commit protocol (``ray_tpu.checkpoint.manifest``).  A commit
(phase 2) is the coordinator's job and may run on any process once every
rank file exists.

Metrics: ``checkpoint_save_seconds`` (persist latency histogram),
``checkpoint_bytes_written``, ``checkpoint_chunks_reused_total`` (dedup
hits).  Spans: ``checkpoint_snapshot`` / ``checkpoint_persist`` in the
``ray_tpu._private.profiling`` recorder lane.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.checkpoint.chunks import ChunkStore
from ray_tpu.checkpoint import manifest as mf
from ray_tpu.checkpoint.tree import IndexFn, flatten_with_paths, full_index


def _save_metrics():
    """Lazy metric handles (internal_kv needs a connected process)."""
    from ray_tpu.util.metrics import Counter, Histogram

    return {
        "seconds": Histogram(
            "checkpoint_save_seconds",
            "per-rank shard persist latency (chunk+hash+write)",
            boundaries=(0.005, 0.02, 0.1, 0.5, 2.0, 10.0)),
        "bytes": Counter("checkpoint_bytes_written",
                         "chunk bytes written by shard persists"),
        "reused": Counter("checkpoint_chunks_reused_total",
                          "chunks deduped against earlier saves"),
    }


def _to_host(leaf) -> Optional[np.ndarray]:
    """One leaf to a host numpy array (None passes through)."""
    if leaf is None:
        return None
    if isinstance(leaf, np.ndarray):
        return np.ascontiguousarray(leaf)
    try:
        import jax

        if isinstance(leaf, jax.Array):
            return np.ascontiguousarray(jax.device_get(leaf))
    except ImportError:
        pass
    arr = np.asarray(leaf)
    if arr.dtype == object:
        raise TypeError(
            f"checkpoint leaves must be arrays/scalars, got object dtype "
            f"for {type(leaf).__name__}")
    return np.ascontiguousarray(arr)


class ShardWriter:
    """One rank's writer into a checkpoint root."""

    def __init__(self, root: str, rank: int = 0, world_size: int = 1,
                 chunk_bytes: Optional[int] = None):
        self.root = root
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.store = ChunkStore(root, chunk_bytes)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.last_stats: Dict[str, Any] = {}

    # ---- phase 0: the bounded pause ----
    def snapshot(self, tree: Any) -> List[Tuple[str, np.ndarray]]:
        """Device→host copy of the local shard, flattened with paths.
        This is the only step-path cost; everything after runs off it."""
        from ray_tpu._private import profiling

        t0 = time.perf_counter()
        host = [(p, _to_host(leaf)) for p, leaf in flatten_with_paths(tree)]
        profiling.record_span("checkpoint_snapshot", t0, time.perf_counter(),
                              rank=self.rank)
        return host

    # ---- phase 1: persist ----
    def persist(self, snapshot: List[Tuple[str, np.ndarray]], step: int,
                index_fn: Optional[IndexFn] = None,
                extra: Optional[dict] = None) -> Dict[str, Any]:
        """Chunk + write the snapshot and publish this rank's shard file.
        Returns persist stats ({"bytes_written", "chunks_reused", ...})."""
        from ray_tpu._private import chaos, profiling

        t0 = time.perf_counter()
        arrays: Dict[str, dict] = {}
        written = 0
        reused = 0
        for path, arr in snapshot:
            if arr is None:
                continue
            gshape_index = index_fn(path, arr) if index_fn else None
            replicated = gshape_index is None
            if replicated:
                gshape = tuple(int(d) for d in arr.shape)
                index = full_index(gshape)
            else:
                gshape, index = gshape_index
            entry = {
                "dtype": str(arr.dtype),
                "shape": [int(d) for d in arr.shape],
                "global_shape": [int(d) for d in gshape],
                "index": [[int(s), int(e)] for s, e in index],
                "nbytes": int(arr.nbytes),
                "replicated": bool(replicated),
                "chunks": None,
            }
            # Replicated arrays are identical on every rank: only rank 0
            # pays the hash+write; the others record metadata only.
            if not replicated or self.rank == 0:
                hashes, w, r = self.store.put_buffer(arr.data)
                entry["chunks"] = hashes
                entry["chunk_size"] = self.store.chunk_bytes
                written += w
                reused += r
            arrays[path] = entry
        meta = {
            "rank": self.rank,
            "world_size": self.world_size,
            "arrays": arrays,
            "extra": dict(extra or {}),
        }
        # Chaos kill site "checkpoint_shard:<rank>:<nth>": dies between the
        # chunk writes and this rank's metadata publish.
        chaos.maybe_die("checkpoint_shard", self.rank)
        mf.write_rank_meta(self.root, step, self.rank, meta)
        t1 = time.perf_counter()
        profiling.record_span("checkpoint_persist", t0, t1,
                              rank=self.rank, step=int(step))
        stats = {"rank": self.rank, "step": int(step),
                 "bytes_written": written, "chunks_reused": reused,
                 "seconds": t1 - t0}
        self.last_stats = stats
        try:
            m = _save_metrics()
            m["seconds"].observe(t1 - t0)
            if written:
                m["bytes"].inc(written)
            if reused:
                m["reused"].inc(reused)
        except Exception:
            pass
        return stats

    def persist_async(self, snapshot: List[Tuple[str, np.ndarray]],
                      step: int, index_fn: Optional[IndexFn] = None,
                      extra: Optional[dict] = None) -> None:
        """Run ``persist`` on a background thread (one at a time per
        writer: a still-running earlier persist is joined first so shard
        files always appear in step order)."""
        self.wait()

        def run():
            try:
                self.persist(snapshot, step, index_fn, extra)
            except BaseException as e:  # noqa: BLE001 — surfaced by wait()
                self._error = e

        self._thread = threading.Thread(
            target=run, daemon=True, name=f"ckpt-persist-r{self.rank}")
        self._thread.start()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Join the in-flight background persist; re-raises its error."""
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError("background checkpoint persist did not "
                                   f"finish within {timeout}s")
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def save_tree(root: str, tree: Any, step: int,
              meta: Optional[dict] = None,
              chunk_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Single-process convenience: snapshot + persist + commit one full
    (world_size=1) tree.  Returns persist stats with the manifest."""
    writer = ShardWriter(root, rank=0, world_size=1, chunk_bytes=chunk_bytes)
    stats = writer.persist(writer.snapshot(tree), step)
    manifest = mf.commit_manifest(root, step, 1, meta=meta)
    mf.gc_orphans(root, below=step)
    stats["manifest"] = manifest
    return stats


def persist_dict_checkpoint(root: str, step: int, data: Dict[str, Any],
                            meta: Optional[dict] = None) -> dict:
    """Persist a plain dict checkpoint under the same commit protocol
    (kind="dict"): payload first, manifest rename last — so manifest
    discovery treats driver-side dict checkpoints and rank-sharded saves
    uniformly."""
    sdir = mf.step_dir(root, step)
    os.makedirs(sdir, exist_ok=True)
    tmp = os.path.join(sdir, mf.DICT_PAYLOAD + ".tmp")
    with open(tmp, "wb") as f:
        pickle.dump(data, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(sdir, mf.DICT_PAYLOAD))
    manifest = mf.commit_manifest(root, step, 1, meta=meta, kind="dict")
    mf.gc_orphans(root, below=step)
    return manifest
