"""Pytree flatten/unflatten + shard-index helpers (numpy-only, jax-free).

Paths are JSON-encoded key lists (``["params","dense",0]``) — unambiguous
for any mix of str/int keys, stable across processes, and reversible, so a
checkpoint can be restored into a nested dict/list skeleton without
pickling a structure template.

Shard indices are per-dimension ``[start, stop]`` pairs against the array's
GLOBAL shape.  ``index is None`` marks a replicated array (every rank holds
the full value): only rank 0 persists its bytes, the other ranks record
metadata only — which is what makes replicated-parameter saves cost one
rank's write instead of N identical ones.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# index_fn(path, local_array) -> (global_shape, index) | None for replicated
IndexFn = Callable[[str, np.ndarray], Optional[Tuple[tuple, list]]]


def _is_leaf(node: Any) -> bool:
    if isinstance(node, (dict,)) or hasattr(node, "items"):
        return False
    if isinstance(node, (list, tuple)):
        return False
    return True


def flatten_with_paths(tree: Any) -> List[Tuple[str, Any]]:
    """Flatten nested Mappings/lists/tuples into (path, leaf) pairs in a
    deterministic order (mapping keys sorted).  ``None`` leaves are kept —
    the saver skips them, the restorer leaves the target's value in place."""
    out: List[Tuple[str, Any]] = []

    def rec(node, keys):
        if hasattr(node, "items") and not _is_leaf(node):
            for k in sorted(node.keys(), key=lambda x: (str(type(x)), x)):
                rec(node[k], keys + [k])
        elif isinstance(node, (list, tuple)):
            for i, child in enumerate(node):
                rec(child, keys + [i])
        else:
            out.append((json.dumps(keys), node))

    rec(tree, [])
    return out


def path_keys(path: str) -> List[Any]:
    return json.loads(path)


def nest_from_paths(values: Dict[str, Any]) -> Any:
    """Rebuild a nested structure from path->value (dicts for str keys,
    lists for int keys).  Tuples/namedtuples degrade to lists — restore
    with a ``target`` to preserve exact container types."""
    if not values:
        return {}
    items = [(path_keys(p), v) for p, v in values.items()]
    if any(not ks for ks, _ in items):
        if len(items) != 1:
            raise ValueError("mixed root leaf and nested paths")
        return items[0][1]

    def build(entries):
        first_keys = {ks[0] for ks, _ in entries}
        as_list = all(isinstance(k, int) for k in first_keys)
        groups: Dict[Any, list] = {}
        for ks, v in entries:
            groups.setdefault(ks[0], []).append((ks[1:], v))
        def value_of(sub):
            if len(sub) == 1 and not sub[0][0]:
                return sub[0][1]
            return build(sub)
        if as_list:
            return [value_of(groups[i]) for i in sorted(groups)]
        return {k: value_of(groups[k]) for k in groups}

    return build(items)


def unflatten_like(target: Any, values: Dict[str, Any]) -> Any:
    """Rebuild ``target``'s structure with leaves replaced from ``values``
    (missing paths keep the target's leaf).  Container types are mirrored:
    Mappings via ``type(target)(dict)`` (falling back to dict), namedtuples
    via ``type(*children)``, lists/tuples as themselves."""

    def rec(node, keys):
        if hasattr(node, "items") and not _is_leaf(node):
            rebuilt = {k: rec(v, keys + [k]) for k, v in node.items()}
            try:
                return type(node)(rebuilt)
            except Exception:
                return rebuilt
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            children = [rec(c, keys + [i]) for i, c in enumerate(node)]
            return type(node)(*children)
        if isinstance(node, (list, tuple)):
            children = [rec(c, keys + [i]) for i, c in enumerate(node)]
            return type(node)(children)
        path = json.dumps(keys)
        if path in values:
            loaded = values[path]
            if node is None:
                return loaded
            # Match the target leaf's flavor: jax arrays stay jax (the
            # caller device_puts afterwards), python scalars stay scalars.
            if isinstance(node, (int, float, bool)) and np.ndim(loaded) == 0:
                return type(node)(loaded.item() if hasattr(loaded, "item")
                                  else loaded)
            return loaded
        return node

    return rec(target, [])


# ---- shard index helpers ----
def full_index(shape) -> list:
    return [[0, int(d)] for d in shape]


def axis0_shard_index(rank: int, world_size: int,
                      should_shard: Optional[Callable[[str], bool]] = None
                      ) -> IndexFn:
    """Save-side index_fn for the even axis-0 split (each rank holds
    ``global_dim0 / world`` rows): derives the global shape from the local
    shard.  Scalars/0-d leaves — and paths ``should_shard`` rejects (e.g.
    replicated biases/optimizer scalars in a mixed layout) — fall back to
    replicated."""

    def fn(path: str, arr: np.ndarray):
        if arr.ndim == 0:
            return None
        if should_shard is not None and not should_shard(path):
            return None
        local0 = int(arr.shape[0])
        gshape = (local0 * world_size,) + tuple(int(d) for d in arr.shape[1:])
        index = full_index(gshape)
        index[0] = [rank * local0, (rank + 1) * local0]
        return gshape, index

    return fn


def axis0_restore_index(rank: int, world_size: int):
    """Restore-side index_fn: which slice of each GLOBAL array this rank
    wants (even split with the remainder spread over the low ranks —
    handles N→M resizes where M doesn't divide the global dim)."""

    def fn(path: str, global_shape) -> Optional[list]:
        if not global_shape:
            return None  # scalar: replicated everywhere
        n = int(global_shape[0])
        base, rem = divmod(n, world_size)
        start = rank * base + min(rank, rem)
        stop = start + base + (1 if rank < rem else 0)
        index = full_index(global_shape)
        index[0] = [start, stop]
        return index

    return fn


def slice_from_index(arr: np.ndarray, index: Optional[list]) -> np.ndarray:
    if index is None:
        return arr
    return arr[tuple(slice(s, e) for s, e in index)]
