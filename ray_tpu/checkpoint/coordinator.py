"""Driver-side save coordination: gang fan-out, background commit, GC.

The coordinator never touches array bytes — ranks write their own shards
(phase 1); the coordinator's only writes are the atomic manifest rename
(phase 2) and garbage collection.  ``commit_when_complete`` polls for the
rank files instead of holding a rendezvous, so persist can be fully
asynchronous worker-side (a pipeline step snapshots and returns; a
background thread writes) and a crashed rank simply times the commit out —
leaving the store at the previous committed checkpoint.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.checkpoint import manifest as mf


def _flow_token():
    # Lazy: the parallel package init pulls jax, and checkpoint's tree
    # plumbing is deliberately importable jax-free (checkpoint/tree.py).
    from ray_tpu.parallel.flow import CancellationToken

    return CancellationToken()


def commit_when_complete(root: str, step: int, world_size: int,
                         meta: Optional[dict] = None,
                         timeout: float = 120.0,
                         poll_interval: float = 0.05,
                         in_progress: Optional[List[int]] = None) -> dict:
    """Wait for every rank's shard file, then commit + sweep orphans.
    Raises TimeoutError (store untouched, previous checkpoint stands) if
    the shards don't all land within ``timeout``.  ``in_progress`` lists
    steps with saves still in flight (e.g. pending async commits) so the
    orphan sweep never deletes a step that is about to commit."""
    from ray_tpu._private import profiling

    t0 = time.perf_counter()
    deadline = time.monotonic() + timeout
    while True:
        missing = mf.missing_rank_files(root, step, world_size)
        if not missing:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"checkpoint step {step}: ranks {missing} never persisted "
                f"their shards within {timeout}s; not committing")
        time.sleep(poll_interval)
    manifest = mf.commit_manifest(root, step, world_size, meta=meta)
    mf.gc_orphans(root, in_progress=in_progress or (), below=step)
    profiling.record_span("checkpoint_commit", t0, time.perf_counter(),
                          step=int(step))
    return manifest


class AsyncCommitter:
    """Background commit threads for async sharded saves.  One commit per
    step; ``flush()`` joins them and re-raises the first failure.

    Each commit thread carries a :class:`ray_tpu.parallel.flow.
    CancellationToken`; ``cancel_pending()`` — wired into MeshGroup
    restart hooks, so gang restart is ONE call — cancels every pending
    step's token (the flow drain contract).  A cancelled commit wakes
    from its poll immediately instead of sleeping it out, and a
    cancelled-then-resaved step simply registers a FRESH token, so stale
    cancellations can never suppress a replayed save."""

    def __init__(self):
        # (thread, token) per step; a re-registered step replaces both.
        self._pending: Dict[int, Tuple[threading.Thread,
                                       CancellationToken]] = {}
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()

    def commit_async(self, root: str, step: int, world_size: int,
                     meta: Optional[dict] = None,
                     timeout: float = 120.0,
                     on_commit: Optional[Callable[[dict], None]] = None
                     ) -> None:
        token = _flow_token()

        def run():
            try:
                poll = 0.05
                deadline = time.monotonic() + timeout
                while True:
                    if not mf.missing_rank_files(root, step, world_size):
                        break
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"checkpoint step {step} commit timed out")
                    # token.wait doubles as the poll sleep: a cancel (gang
                    # restart killed the writers) wakes and exits NOW.
                    if token.wait(poll):
                        return
                if token.cancelled:
                    return
                manifest = mf.commit_manifest(root, step, world_size,
                                              meta=meta)
                # Sibling commits still pending (e.g. step N while we are
                # N+1) have fully persisted, manifest-less dirs — exempt
                # them from the sweep or we'd destroy a valid save in the
                # window between its poll and its manifest rename.
                with self._lock:
                    pending = [s for s in self._pending if s != int(step)]
                mf.gc_orphans(root, in_progress=pending, below=step)
                if on_commit is not None:
                    on_commit(manifest)
            except BaseException as e:  # noqa: BLE001 — surfaced by flush
                with self._lock:
                    self._errors.append(e)
            finally:
                with self._lock:
                    # A cancelled-then-resaved step re-registers under the
                    # same key: only deregister if we still own it.
                    entry = self._pending.get(int(step))
                    if entry is not None and entry[0] is t:
                        self._pending.pop(int(step), None)

        t = threading.Thread(target=run, daemon=True,
                             name=f"ckpt-commit-{step}")
        with self._lock:
            # A fresh save supersedes any stale cancellation of this step
            # (a restart can roll training back and replay through a step
            # whose earlier save was cancelled): the fresh thread owns a
            # fresh token the stale cancel never touched.
            self._pending[int(step)] = (t, token)
        t.start()

    def cancel_pending(self) -> None:
        """Abandon uncommitted saves (e.g. after a gang restart killed the
        writers): their step dirs become orphans for the next GC."""
        with self._lock:
            tokens = [tok for _, tok in self._pending.values()]
        for tok in tokens:
            tok.cancel()

    def pending_steps(self) -> List[int]:
        """Steps whose commit threads are still registered."""
        with self._lock:
            return list(self._pending.keys())

    def flush(self, timeout: Optional[float] = None) -> None:
        with self._lock:
            threads = [t for t, _ in self._pending.values()]
        for t in threads:
            t.join(timeout)
        with self._lock:
            errors, self._errors = self._errors, []
        if errors:
            raise errors[0]


def _rank_persist_shard(state, root, step, tree_fn, sync, extra):
    """Worker-side (run_stateful shape): snapshot this rank's tree and
    persist it — synchronously, or on the rank's background thread."""
    import os

    from ray_tpu.checkpoint.saver import ShardWriter

    rank = int(os.environ.get("RTPU_RANK", "0"))
    world = int(os.environ.get("RTPU_WORLD_SIZE", "1"))
    writer = state.get("_ckpt_writer")
    if writer is None or writer.root != root:
        writer = ShardWriter(root, rank, world)
        state["_ckpt_writer"] = writer
    snap = writer.snapshot(tree_fn(state))
    if sync:
        writer.persist(snap, step, extra=extra)
    else:
        writer.persist_async(snap, step, extra=extra)
    return {"rank": rank, "step": int(step)}


def _rank_wait_persisted(state, timeout):
    writer = state.get("_ckpt_writer")
    if writer is not None:
        writer.wait(timeout)
    return True


class DistributedCheckpointer:
    """Sharded checkpointing over a MeshGroup gang.

    ``tree_fn(state) -> pytree`` (picklable) extracts the rank's local
    tree from its worker state dict.  ``save()`` is the lockstep form;
    ``save_async()`` overlaps persist with the step stream: ranks snapshot
    (the bounded pause) and return, chunk writes ride rank background
    threads, and a driver-side committer publishes the manifest when the
    shards land.  ``num_to_keep`` evicts old committed steps (and their
    now-unreferenced chunks) after each commit.
    """

    def __init__(self, group, root: str,
                 tree_fn: Callable[[dict], Any],
                 num_to_keep: Optional[int] = None,
                 commit_timeout: float = 120.0):
        self.group = group
        self.root = root
        self.tree_fn = tree_fn
        self.num_to_keep = num_to_keep
        self.commit_timeout = commit_timeout
        self.committer = AsyncCommitter()
        self.last_manifest: Optional[dict] = None
        # In-flight async saves die with the gang: stop their committers
        # from publishing a half-written step after a rebuild.
        if hasattr(group, "add_restart_hook"):
            group.add_restart_hook(lambda g: self.committer.cancel_pending())

    def _post_commit(self, manifest: dict) -> None:
        self.last_manifest = manifest
        if self.num_to_keep:
            try:
                mf.evict_steps(self.root, self.num_to_keep)
            except Exception:
                pass

    def save(self, step: int, meta: Optional[dict] = None) -> dict:
        """Lockstep sharded save: every rank persists, then commit."""
        self.group.run_stateful(_rank_persist_shard, self.root, step,
                                self.tree_fn, True, meta)
        manifest = commit_when_complete(self.root, step,
                                        self.group.num_hosts, meta=meta,
                                        timeout=self.commit_timeout,
                                        in_progress=self.committer
                                        .pending_steps())
        self._post_commit(manifest)
        return manifest

    def save_async(self, step: int, meta: Optional[dict] = None) -> None:
        """Async sharded save: ranks snapshot and return (persist runs on
        their background threads); the manifest commits from a driver
        thread when every shard lands."""
        self.group.run_stateful(_rank_persist_shard, self.root, step,
                                self.tree_fn, False, meta)
        self.committer.commit_async(self.root, step, self.group.num_hosts,
                                    meta=meta, timeout=self.commit_timeout,
                                    on_commit=self._post_commit)

    def flush(self, timeout: Optional[float] = None) -> None:
        """Barrier: worker persists joined, pending commits published."""
        self.group.run_stateful(_rank_wait_persisted, self.commit_timeout)
        self.committer.flush(timeout)

    def latest_step(self) -> Optional[int]:
        return mf.latest_committed_step(self.root)
