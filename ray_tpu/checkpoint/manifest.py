"""Step directories, rank shard metadata, and the atomic commit protocol.

Two-phase commit:

1. **Persist** — each rank writes its chunks into the content-addressed
   store, then its ``rank_<r>.json`` shard metadata into the step dir
   (tmp + ``os.replace``, so a rank file is never half-written).
2. **Commit** — once every rank file exists, ONE writer (the coordinator)
   writes ``MANIFEST.json`` via tmp + atomic rename.  The manifest is the
   existence predicate: readers only ever look at steps that have one, so
   a crash anywhere before the rename leaves the previous committed
   checkpoint as the latest — never a partial view.

Aborted saves (step dirs without a manifest) are swept by ``gc_orphans``
on the next commit; chunks referenced by no committed manifest are swept
by ``gc_chunks`` after eviction.
"""
from __future__ import annotations

import json
import os
import shutil
import time
import uuid
from typing import Dict, Iterable, List, Optional, Set

from ray_tpu.checkpoint.chunks import ChunkStore

MANIFEST_FILE = "MANIFEST.json"
STEPS_DIR = "steps"
_STEP_FMT = "step_{:08d}"
_RANK_FMT = "rank_{:05d}.json"
DICT_PAYLOAD = "checkpoint.pkl"


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, STEPS_DIR, _STEP_FMT.format(int(step)))


def rank_file(sdir: str, rank: int) -> str:
    return os.path.join(sdir, _RANK_FMT.format(int(rank)))


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = path + f".tmp_{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_rank_meta(root: str, step: int, rank: int, meta: dict) -> str:
    sdir = step_dir(root, step)
    os.makedirs(sdir, exist_ok=True)
    path = rank_file(sdir, rank)
    _atomic_write_json(path, meta)
    return path


def missing_rank_files(root: str, step: int, world_size: int) -> List[int]:
    sdir = step_dir(root, step)
    return [r for r in range(world_size)
            if not os.path.exists(rank_file(sdir, r))]


def commit_manifest(root: str, step: int, world_size: int,
                    meta: Optional[dict] = None,
                    kind: str = "sharded") -> dict:
    """Phase 2: atomically publish ``step`` as committed.  Raises
    ``FileNotFoundError`` if any rank's shard file is missing — commit
    must never outrun persist."""
    from ray_tpu._private import chaos

    sdir = step_dir(root, step)
    if kind == "sharded":
        missing = missing_rank_files(root, step, world_size)
        if missing:
            raise FileNotFoundError(
                f"cannot commit step {step}: missing shard files for "
                f"ranks {missing} under {sdir}")
    manifest = {
        "kind": kind,
        "step": int(step),
        "world_size": int(world_size),
        "created_at": time.time(),
        "meta": dict(meta or {}),
    }
    # Chaos kill site: a schedule entry "checkpoint_commit:0:<nth>" SIGKILLs
    # here — after every shard persisted, before the atomic publish — the
    # exact window the two-phase protocol must make invisible to readers.
    chaos.maybe_die("checkpoint_commit", 0)
    _atomic_write_json(os.path.join(sdir, MANIFEST_FILE), manifest)
    try:
        _commit_metrics()
    except Exception:
        pass
    return manifest


def _commit_metrics() -> None:
    from ray_tpu.util.metrics import Counter

    Counter("checkpoint_commits_total",
            "committed distributed checkpoints").inc()


def read_manifest(root: str, step: int) -> dict:
    with open(os.path.join(step_dir(root, step), MANIFEST_FILE)) as f:
        return json.load(f)


def _step_of(name: str) -> Optional[int]:
    if not name.startswith("step_"):
        return None
    try:
        return int(name[len("step_"):])
    except ValueError:
        return None


def all_steps(root: str) -> List[int]:
    """Every step dir on disk, committed or not."""
    d = os.path.join(root, STEPS_DIR)
    if not os.path.isdir(d):
        return []
    steps = [_step_of(n) for n in os.listdir(d)]
    return sorted(s for s in steps if s is not None)


def committed_steps(root: str) -> List[int]:
    return [s for s in all_steps(root)
            if os.path.exists(os.path.join(step_dir(root, s), MANIFEST_FILE))]


def latest_committed_step(root: str) -> Optional[int]:
    steps = committed_steps(root)
    return steps[-1] if steps else None


def load_rank_metas(root: str, step: int) -> List[dict]:
    manifest = read_manifest(root, step)
    sdir = step_dir(root, step)
    metas = []
    for r in range(manifest["world_size"]):
        with open(rank_file(sdir, r)) as f:
            metas.append(json.load(f))
    return metas


def delete_step(root: str, step: int) -> None:
    shutil.rmtree(step_dir(root, step), ignore_errors=True)


def gc_orphans(root: str, in_progress: Iterable[int] = (),
               below: Optional[int] = None) -> List[int]:
    """Sweep aborted saves: step dirs with no manifest that aren't part of
    a save currently in flight.  ``below`` (the committing step) bounds
    the sweep — steps ABOVE it may be concurrent saves still persisting
    their shards (async pipelines overlap save N+1 with N's commit), so
    only steps strictly below are provably dead; a crashed newer step is
    swept by the next, higher-numbered commit.  Returns deleted steps."""
    keep = set(int(s) for s in in_progress)
    committed = set(committed_steps(root))
    deleted = []
    for s in all_steps(root):
        if s in committed or s in keep:
            continue
        if below is not None and s >= below:
            continue
        delete_step(root, s)
        deleted.append(s)
    if deleted:
        try:
            from ray_tpu.util.metrics import Counter

            Counter("checkpoint_gc_orphans_total",
                    "aborted partial saves garbage-collected").inc(
                        len(deleted))
        except Exception:
            pass
    return deleted


def referenced_chunks(root: str) -> Set[str]:
    """Chunks referenced by ANY shard file on disk — committed or not: an
    in-flight async save's chunks must survive a concurrent eviction's
    sweep (its step dir only becomes collectable once gc_orphans removes
    it, after which the next sweep reclaims the chunks)."""
    refs: Set[str] = set()
    for s in all_steps(root):
        sdir = step_dir(root, s)
        try:
            names = os.listdir(sdir)
        except OSError:
            continue  # concurrently evicted
        for name in names:
            if not (name.startswith("rank_") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(sdir, name)) as f:
                    meta = json.load(f)
                for arr in meta.get("arrays", {}).values():
                    refs.update(arr.get("chunks") or ())
            except (OSError, json.JSONDecodeError, KeyError, AttributeError):
                continue
    return refs


def gc_chunks(root: str) -> int:
    """Delete chunks no committed manifest references; returns count."""
    return ChunkStore(root).gc(referenced_chunks(root))


def evict_steps(root: str, num_to_keep: int) -> List[int]:
    """Delete the oldest committed steps beyond ``num_to_keep``, then sweep
    now-unreferenced chunks.  Returns the evicted steps."""
    steps = committed_steps(root)
    evicted = steps[:-num_to_keep] if num_to_keep > 0 else []
    for s in evicted:
        delete_step(root, s)
    if evicted:
        gc_chunks(root)
    return evicted
