"""Content-addressed chunk store: the dedup layer under sharded saves.

Array bytes are split into fixed-size chunks; each chunk is stored once
under its content hash (``chunks/<hh>/<hash>``).  A re-save of unchanged
state hashes to the same names and writes nothing — frequent checkpoints
pay only for the chunks that actually changed (the hard-link-style reuse
from incremental checkpointing, done by reference instead of by link so
eviction is a plain unreferenced-chunk sweep).

Writes are atomic (tmp file + ``os.replace``): a chunk file either exists
with its full content or not at all, so a crash mid-save can never corrupt
a chunk another manifest already references.
"""
from __future__ import annotations

import hashlib
import os
import time
import uuid
from typing import Iterator, List, Optional, Set, Tuple

CHUNK_BYTES_ENV = "RAY_TPU_CHECKPOINT_CHUNK_BYTES"
_DEFAULT_CHUNK_BYTES = 1 << 20  # 1 MiB

GC_GRACE_ENV = "RAY_TPU_CHECKPOINT_GC_GRACE_SECONDS"
_DEFAULT_GC_GRACE = 300.0

CHUNKS_DIR = "chunks"


def default_chunk_bytes() -> int:
    try:
        return max(4096, int(os.environ.get(CHUNK_BYTES_ENV,
                                            _DEFAULT_CHUNK_BYTES)))
    except ValueError:
        return _DEFAULT_CHUNK_BYTES


def gc_grace_seconds() -> float:
    try:
        return max(0.0, float(os.environ.get(GC_GRACE_ENV,
                                             _DEFAULT_GC_GRACE)))
    except ValueError:
        return _DEFAULT_GC_GRACE


def hash_chunk(view) -> str:
    # blake2b: ~2x sha256 throughput; 20 bytes is plenty for a store that
    # holds thousands, not trillions, of chunks.
    return hashlib.blake2b(view, digest_size=20).hexdigest()


def split_chunks(buf, chunk_bytes: int) -> Iterator[memoryview]:
    view = memoryview(buf).cast("B")
    for off in range(0, len(view), chunk_bytes):
        yield view[off:off + chunk_bytes]
    if len(view) == 0:
        yield view  # zero-size arrays still get one (empty) chunk


class ChunkStore:
    """The ``chunks/`` directory of one checkpoint root."""

    def __init__(self, root: str, chunk_bytes: Optional[int] = None):
        self.root = root
        self.dir = os.path.join(root, CHUNKS_DIR)
        self.chunk_bytes = chunk_bytes or default_chunk_bytes()

    def _path(self, h: str) -> str:
        return os.path.join(self.dir, h[:2], h)

    def put(self, view) -> Tuple[str, int]:
        """Store one chunk; returns (hash, bytes_written) — 0 bytes when
        the chunk already exists (dedup hit)."""
        h = hash_chunk(view)
        path = self._path(h)
        if os.path.exists(path):
            # Refresh mtime so a dedup-reused chunk counts as "young" to a
            # concurrent gc(): without this, a chunk referenced only by a
            # step being evicted could be swept in the window between this
            # existence check and our rank file publishing.
            try:
                os.utime(path, None)
            except OSError:
                pass
            return h, 0
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = os.path.join(self.dir, f".tmp_{uuid.uuid4().hex}")
        with open(tmp, "wb") as f:
            f.write(view)
        # Concurrent writers of the same content race benignly: both tmp
        # files hold identical bytes and replace() is atomic.
        os.replace(tmp, path)
        return h, len(view)

    def put_buffer(self, buf) -> Tuple[List[str], int, int]:
        """Chunk + store a whole buffer; returns (hashes, bytes_written,
        chunks_reused)."""
        hashes: List[str] = []
        written = 0
        reused = 0
        for view in split_chunks(buf, self.chunk_bytes):
            h, w = self.put(view)
            hashes.append(h)
            written += w
            if w == 0 and len(view):
                reused += 1
        return hashes, written, reused

    def read(self, h: str) -> bytes:
        with open(self._path(h), "rb") as f:
            return f.read()

    def read_into(self, hashes: List[str], dest) -> None:
        """Reassemble a chunk list into a writable buffer."""
        view = memoryview(dest).cast("B")
        off = 0
        for h in hashes:
            data = self.read(h)
            view[off:off + len(data)] = data
            off += len(data)
        if off != len(view):
            raise ValueError(
                f"chunk list reassembles to {off} bytes, buffer wants "
                f"{len(view)}")

    def known_chunks(self) -> Set[str]:
        out: Set[str] = set()
        if not os.path.isdir(self.dir):
            return out
        for sub in os.listdir(self.dir):
            p = os.path.join(self.dir, sub)
            if not os.path.isdir(p):
                continue
            out.update(os.listdir(p))
        return out

    def gc(self, referenced: Set[str],
           grace_seconds: Optional[float] = None) -> int:
        """Delete chunks not in ``referenced``; returns deleted count.

        Chunks younger than the grace window are kept even when
        unreferenced: a rank persist writes (or utime-refreshes) its
        chunks BEFORE publishing its rank file, so a concurrent sweep
        computed from on-disk rank files would otherwise delete chunks an
        about-to-commit step needs.  Also unlinks stale ``.tmp_*`` files
        left in the store root by writers that crashed between the tmp
        write and ``os.replace`` (``known_chunks`` never sees those, so
        no other sweep reclaims them)."""
        grace = gc_grace_seconds() if grace_seconds is None else grace_seconds
        cutoff = time.time() - grace
        deleted = 0
        for h in self.known_chunks() - set(referenced):
            path = self._path(h)
            try:
                if os.path.getmtime(path) > cutoff:
                    continue
                os.remove(path)
                deleted += 1
            except OSError:
                pass
        if os.path.isdir(self.dir):
            for name in os.listdir(self.dir):
                if not name.startswith(".tmp_"):
                    continue
                p = os.path.join(self.dir, name)
                try:
                    if os.path.getmtime(p) <= cutoff:
                        os.remove(p)
                except OSError:
                    pass
        return deleted
