"""Resharded restore: N-rank checkpoints onto M-rank gangs.

Every array in a committed manifest carries its GLOBAL shape plus, per
saving rank, the ``[start, stop]`` index of the shard that rank held.
Restore therefore doesn't care what the saving topology was: it assembles
each global array from whichever shards cover it (replicated arrays come
from rank 0's chunks alone), then hands the restoring rank the slice IT
wants via a restore-side index_fn — so a 4-rank save restores onto 2
ranks, 8 ranks, or a single process unchanged.
"""
from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.checkpoint.chunks import ChunkStore
from ray_tpu.checkpoint import manifest as mf
from ray_tpu.checkpoint.tree import nest_from_paths, slice_from_index, \
    unflatten_like


def _resolve_step(root: str, step: Optional[int]) -> int:
    if step is None:
        step = mf.latest_committed_step(root)
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {root!r}")
    return int(step)


def assemble_arrays(root: str, step: Optional[int] = None,
                    paths: Optional[List[str]] = None,
                    replicated_out: Optional[Dict[str, bool]] = None
                    ) -> Dict[str, np.ndarray]:
    """Reassemble full GLOBAL arrays from a committed step's shards.
    ``paths`` restricts to a subset (all arrays otherwise);
    ``replicated_out`` — when given — collects each array's replicated
    flag from the shard metadata."""
    step = _resolve_step(root, step)
    store = ChunkStore(root)
    metas = mf.load_rank_metas(root, step)
    out: Dict[str, np.ndarray] = {}
    filled: Dict[str, int] = {}
    for meta in metas:
        for path, entry in meta.get("arrays", {}).items():
            if paths is not None and path not in paths:
                continue
            if replicated_out is not None:
                replicated_out[path] = bool(entry.get("replicated"))
            if entry.get("chunks") is None:
                continue  # replicated shadow entry (rank>0): no bytes
            gshape = tuple(entry["global_shape"])
            dtype = np.dtype(entry["dtype"])
            if path not in out:
                out[path] = np.empty(gshape, dtype=dtype)
                filled[path] = 0
            if entry.get("replicated") and filled[path]:
                continue  # already assembled from another rank
            dest = out[path][tuple(slice(s, e) for s, e in entry["index"])]
            shard = np.empty(tuple(entry["shape"]), dtype=dtype)
            store.read_into(entry["chunks"], shard)
            dest[...] = shard
            filled[path] += int(shard.nbytes)
    for path, arr in out.items():
        if filled[path] < arr.nbytes:
            raise ValueError(
                f"checkpoint step {step} array {path!r} is under-covered: "
                f"{filled[path]}/{arr.nbytes} bytes of the global shape "
                f"were persisted")
    return out


def restore_tree(root: str, step: Optional[int] = None,
                 target: Any = None,
                 index_fn: Optional[Callable] = None) -> Any:
    """Restore a committed checkpoint, optionally resharded.

    ``index_fn(path, global_shape) -> index | None`` picks the restoring
    rank's slice of each global array (None = the full array; the default
    for replicated restores) — build one with ``axis0_restore_index(rank,
    world_size)`` for the even data-parallel split.  With ``target`` the
    exact container structure
    (FrozenDicts, namedtuple optimizer states, scalars) is mirrored;
    without it a nested dict/list skeleton is rebuilt from the paths.

    Dict-kind checkpoints (driver-side ``persist_dict_checkpoint``) return
    the unpickled payload dict.
    """
    step = _resolve_step(root, step)
    manifest = mf.read_manifest(root, step)
    if manifest.get("kind") == "dict":
        import os

        with open(os.path.join(mf.step_dir(root, step),
                               mf.DICT_PAYLOAD), "rb") as f:
            return pickle.load(f)
    replicated: Dict[str, bool] = {}
    arrays = assemble_arrays(root, step, replicated_out=replicated)
    if index_fn is not None:
        # Arrays the manifest marks replicated restore in full on every
        # rank; the index_fn only reshards the genuinely sharded ones.
        arrays = {p: (a if replicated.get(p) else np.ascontiguousarray(
                      slice_from_index(a, index_fn(p, a.shape))))
                  for p, a in arrays.items()}
    if target is not None:
        return unflatten_like(target, arrays)
    return nest_from_paths(arrays)
