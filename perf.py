"""Core microbenchmarks (reference: python/ray/_private/ray_perf.py:93 and
release/microbenchmark/ — tasks/s, actor calls/s, put/get throughput).

Run:  python perf.py [--out PERF.json]
Emits one JSON object with every metric; the reference's published envelope
(release/benchmarks/README.md:5-31) is the comparison bar.
"""
import argparse
import json
import time

import numpy as np

MB = 1024 * 1024


def timed(n, fn, trials=1):
    best_rate, best_dt = 0.0, float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        if n / dt > best_rate:
            best_rate, best_dt = n / dt, dt
    return best_rate, best_dt


def bench_tasks(ray_tpu, n=10000):
    @ray_tpu.remote
    def nop():
        return None

    # Warm the worker pool AND the lease cache (leases are granted as
    # spawned workers register; steady state is what's being measured).
    for _ in range(3):
        ray_tpu.get([nop.remote() for _ in range(2000)])

    def run():
        ray_tpu.get([nop.remote() for _ in range(n)])

    return timed(n, run, trials=3)


def bench_actor_calls(ray_tpu, n=15000):
    @ray_tpu.remote
    class A:
        def nop(self):
            return None

    a = A.remote()
    ray_tpu.get([a.nop.remote() for _ in range(2000)])

    def run():
        ray_tpu.get([a.nop.remote() for _ in range(n)])

    return timed(n, run, trials=3)


def bench_actor_calls_async(ray_tpu, n=15000):
    """Pipelined submission depth via max_concurrency (the reference's
    '1:1 async actor calls' workload)."""
    @ray_tpu.remote
    class A:
        def nop(self):
            return None

    a = A.options(max_concurrency=8).remote()
    ray_tpu.get([a.nop.remote() for _ in range(2000)])

    def run():
        ray_tpu.get([a.nop.remote() for _ in range(n)])

    return timed(n, run, trials=3)


def _drain_put_refs(ray_tpu):
    """Flush the deferred ref-gc queue so dropped put refs are freed (and
    their pool segments recycled) before the next timed round."""
    import time as _t

    from ray_tpu._private.worker import global_worker

    global_worker._drain_ref_gc_queue()
    _t.sleep(0.02)


def bench_put_gbps(ray_tpu, size=64 * MB, n=8):
    """Steady-state large-put bandwidth: after warmup the segment pool
    serves every put from a recycled, pre-faulted segment, so the measured
    path is pack_into's (parallel) memcpy + the seal notify — the envelope
    a training loop putting same-shaped batches every step actually sees.
    The first cold round (fresh segments, kernel page-zeroing) is reported
    separately as put_cold_gb_per_s."""
    data = np.random.randint(0, 255, size, dtype=np.uint8)

    def run():
        refs = [ray_tpu.put(data) for _ in range(n)]
        del refs

    t0 = time.perf_counter()
    run()
    cold_dt = time.perf_counter() - t0
    _drain_put_refs(ray_tpu)

    run()  # second warmup: every size class now pooled
    _drain_put_refs(ray_tpu)
    best_dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        best_dt = min(best_dt, time.perf_counter() - t0)
        _drain_put_refs(ray_tpu)  # recycle between trials, outside timing
    return n * size / best_dt / 1e9, n * size / cold_dt / 1e9


def bench_memcpy_gbps(size=256 * MB):
    """Single-core memcpy ceiling on THIS box — the context for
    put_gb_per_s: cold puts first-touch fresh arena pages, so the bound
    is host memory bandwidth, not the store software (warmed re-puts of
    cached segments measure >4 GB/s)."""
    src = np.random.randint(0, 255, size, dtype=np.uint8)
    dst = bytearray(size)
    t0 = time.perf_counter()
    memoryview(dst)[:] = src.data
    dt = time.perf_counter() - t0
    return size / dt / 1e9, dt


def bench_get_gbps(ray_tpu, size=64 * MB, n=8):
    data = np.random.randint(0, 255, size, dtype=np.uint8)
    refs = [ray_tpu.put(data) for _ in range(n)]
    # Drop the driver-side value cache so get() actually resolves.
    from ray_tpu._private.worker import global_worker

    def run():
        for r in refs:
            global_worker._value_cache.clear()
            ray_tpu.get(r)

    rate, dt = timed(n, run)
    return n * size / dt / 1e9, dt


def bench_put_small(ray_tpu, n=2000):
    def run():
        for i in range(n):
            ray_tpu.put(i)

    return timed(n, run, trials=3)


def bench_checkpoint(size=64 * MB, chunk=1 * MB):
    """Sharded checkpoint store envelope (pure filesystem, no cluster):
    cold save seconds/bytes for `size` of state, then an identical re-save
    (the dedup fast path — only changed chunks pay) and a 1-chunk-mutated
    incremental save.  Reported as checkpoint_save_seconds /
    checkpoint_bytes_written to match the runtime metrics' names."""
    import shutil
    import tempfile

    from ray_tpu.checkpoint import save_tree

    root = tempfile.mkdtemp(prefix="rtpu_ckpt_bench_")
    try:
        n_arrays = 8
        per = size // n_arrays
        tree = {f"w{i}": np.random.randint(
                    0, 255, per, dtype=np.uint8).reshape(-1, 1024)
                for i in range(n_arrays)}
        t0 = time.perf_counter()
        cold = save_tree(root, tree, step=1, chunk_bytes=chunk)
        cold_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        dedup = save_tree(root, tree, step=2, chunk_bytes=chunk)
        dedup_s = time.perf_counter() - t0
        tree["w0"][:chunk // 1024] += 1  # dirty exactly ~one chunk
        t0 = time.perf_counter()
        incr = save_tree(root, tree, step=3, chunk_bytes=chunk)
        incr_s = time.perf_counter() - t0
        return {
            "checkpoint_save_seconds": cold_s,
            "checkpoint_bytes_written": cold["bytes_written"],
            "checkpoint_save_gb_per_s": size / cold_s / 1e9,
            "checkpoint_dedup_save_seconds": dedup_s,
            "checkpoint_dedup_bytes_written": dedup["bytes_written"],
            "checkpoint_incremental_bytes_written": incr["bytes_written"],
            "checkpoint_incremental_save_seconds": incr_s,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_rollout_plane(ray_tpu, fragments=24, num_workers=2, num_envs=4,
                        fragment_length=64):
    """Streaming rollout-plane envelope (no learner, native CPU env): the
    driver consumes fragments from the SampleStream as fast as the worker
    pool produces them, publishing a weight version every 4 fragments.
    Reports fragments/s, env-steps/s, the weight-staleness histogram, and
    the worker idle fraction — the same keys the bench's real-env PPO now
    records, measured on the plane alone."""
    import jax

    from ray_tpu.rllib import PPOConfig
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.env.py_envs import make_py_env
    from ray_tpu.rllib.evaluation.sample_stream import SampleStream
    from ray_tpu.rllib.evaluation.worker_set import WorkerSet

    config = (PPOConfig().environment("CartPole-v1")
              .rollouts(num_rollout_workers=num_workers,
                        num_envs_per_worker=num_envs,
                        rollout_fragment_length=fragment_length,
                        mode="actor")
              .training(model={"fcnet_hiddens": [32]}))
    spec = RLModuleSpec.for_env(make_py_env("CartPole-v1"),
                                tuple(config.hiddens))
    workers = WorkerSet(config, spec)
    stream = SampleStream(workers, kind="gae", max_in_flight_per_worker=2,
                          max_weight_staleness=4)
    module = spec.build()
    params = module.init(jax.random.PRNGKey(0), spec.example_obs())
    stream.publish_weights(params)
    stream.next_fragment(timeout=60.0)  # warmup: jit compiles on workers
    t0 = time.perf_counter()
    got = 0
    for i in range(fragments):
        if stream.next_fragment(timeout=60.0) is None:
            break
        got += 1
        if (i + 1) % 4 == 0:
            stream.publish_weights(params)
    dt = time.perf_counter() - t0
    st = stream.stats()
    stream.close()
    workers.stop()
    steps = got * num_envs * fragment_length
    return {
        "rollout_fragments_per_s": got / dt,
        "rollout_steps_per_s": steps / dt,
        "rollout_worker_idle_frac": st["worker_idle_frac"],
        "rollout_weight_lag_hist": st["weight_lag_hist"],
        "rollout_stale_dropped": st["stale_dropped"],
    }


def bench_put_many_small(ray_tpu, n=2000, k=100):
    """Batched small puts: put_many coalesces the control plane, so the
    per-object cost is serialization + owner-store insert only."""
    def run():
        for base in range(0, n, k):
            ray_tpu.put_many(list(range(base, base + k)))

    return timed(n, run, trials=3)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    p.add_argument("--native-arena", default="1",
                   help="RAY_TPU_NATIVE_STORE value (1=arena, 0=segments)")
    args = p.parse_args()
    import os

    os.environ["RAY_TPU_NATIVE_STORE"] = args.native_arena
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=1024 * MB)
    out = {}
    try:
        out["tasks_per_s"], _ = bench_tasks(ray_tpu)
        out["actor_calls_per_s"], _ = bench_actor_calls(ray_tpu)
        out["async_actor_calls_per_s"], _ = bench_actor_calls_async(ray_tpu)
        out["put_small_per_s"], _ = bench_put_small(ray_tpu)
        out["put_many_small_per_s"], _ = bench_put_many_small(ray_tpu)
        out["put_gb_per_s"], out["put_cold_gb_per_s"] = \
            bench_put_gbps(ray_tpu)
        out["memcpy_gb_per_s"], _ = bench_memcpy_gbps()
        out["get_gb_per_s"], _ = bench_get_gbps(ray_tpu)
        out.update(bench_checkpoint())
        out.update(bench_rollout_plane(ray_tpu))
        out = {k: (round(v, 2) if isinstance(v, float) else v)
               for k, v in out.items()}
        out["store"] = "arena" if args.native_arena == "1" else "segments"
        # Reference envelope for eyeballing (single node, release/
        # benchmarks/README.md: cluster-wide numbers; ray_perf.py runs
        # are per-process like these).
        out["reference_note"] = (
            "ray_perf.py-style single-process workloads; reference "
            "envelope: ~1k-10k tasks/s, ~5-10k actor calls/s per core, "
            "plasma put/get multiple GB/s")
    finally:
        ray_tpu.shutdown()
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
